"""Pass 3 — determinism.

The replayable subsystems — sigpipe, gossip, txn, scenario, ssz — must
make every *decision* on injected clocks (utils/clock.py) and seeded
RNG: a seeded chaos schedule or scenario must replay bit-identically,
and a wall-clock read or a draw from process-global entropy anywhere in
those paths breaks the ``(scenario, seed)`` determinism pin.

Policy boundaries (docs/analysis.md):

* ``time.perf_counter`` is allowed — metrics *measure* on wall clock,
  decisions must not (the utils/clock.py contract).
* The resilience supervisor's watchdog is exempt by scope: it times a
  real worker thread no virtual clock can advance, and lives in
  ``resilience/`` which this pass does not scan.
* ``random.Random(seed)`` is the required idiom; the module-global
  functions (``random.random()`` …) and zero-arg ``Random()`` are
  process-shared or OS-seeded and flagged.
"""
from __future__ import annotations

import ast

from .core import Context, Finding

_SCOPE = (
    "consensus_specs_tpu.sigpipe",
    "consensus_specs_tpu.gossip",
    "consensus_specs_tpu.txn",
    "consensus_specs_tpu.scenario",
    "consensus_specs_tpu.ssz",
)

_WALL_CLOCK = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.sleep", "time.localtime", "time.gmtime", "time.ctime",
    "datetime.now", "datetime.utcnow", "datetime.today", "date.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})

_ENTROPY = frozenset({
    "os.urandom", "uuid.uuid1", "uuid.uuid4",
})

_GLOBAL_RNG_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "getrandbits", "gauss", "normalvariate",
    "expovariate", "betavariate", "seed", "randbytes",
})


def _dotted(expr: ast.expr) -> str | None:
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


# stdlib modules whose import aliases must be tracked so that
# `import time as t` / `from time import time` cannot dodge the gate
_TRACKED_MODULES = ("time", "random", "os", "datetime", "secrets",
                    "uuid", "numpy", "np")


def _alias_map(tree: ast.AST) -> dict[str, str]:
    """local name -> canonical dotted prefix, for the tracked modules."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root in _TRACKED_MODULES:
                    aliases[(a.asname or a.name).split(".")[0]] = root
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and \
                node.module and node.module.split(".")[0] in \
                _TRACKED_MODULES:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _canonical(name: str, aliases: dict[str, str]) -> str:
    head, _, tail = name.partition(".")
    mapped = aliases.get(head)
    if mapped is None:
        return name
    return f"{mapped}.{tail}" if tail else mapped


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for sf in ctx.files:
        if not sf.in_module(*_SCOPE):
            continue
        aliases = _alias_map(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name is None:
                continue
            name = _canonical(name, aliases)
            if name in _WALL_CLOCK:
                findings.append(Finding(
                    "det-wall-clock", sf.rel, node.lineno,
                    node.col_offset,
                    f"decision path calls {name}() — wall clock reads "
                    f"break seeded replay",
                    hint="take a clock object (utils/clock.py contract); "
                         "time.perf_counter is allowed for measurement"))
            elif name in _ENTROPY or name.startswith("secrets.") \
                    or name.startswith("numpy.random.") \
                    or name.startswith("np.random."):
                findings.append(Finding(
                    "det-unseeded-rng", sf.rel, node.lineno,
                    node.col_offset,
                    f"decision path draws from {name}() — process/OS "
                    f"entropy breaks seeded replay",
                    hint="derive from a seeded random.Random owned by "
                         "the caller"))
            elif name.startswith("random.") \
                    and name.split(".", 1)[1] in _GLOBAL_RNG_FNS:
                findings.append(Finding(
                    "det-unseeded-rng", sf.rel, node.lineno,
                    node.col_offset,
                    f"{name}() uses the process-global RNG — shared, "
                    f"unseeded state breaks seeded replay and per-node "
                    f"isolation",
                    hint="use a seeded random.Random instance"))
            elif name == "random.Random" and not node.args \
                    and not node.keywords:
                findings.append(Finding(
                    "det-unseeded-rng", sf.rel, node.lineno,
                    node.col_offset,
                    "Random() without a seed is OS-seeded — schedules "
                    "built from it can never replay",
                    hint="pass an explicit seed"))
    return findings
