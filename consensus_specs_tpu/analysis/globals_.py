"""Pass 4 — global state.

The scenario harness (PR 7) runs N simulated nodes in one process;
its isolation invariant is that observable per-node state lives behind
``utils.nodectx.Router`` (the pattern ``resilience.INCIDENTS`` and
``sigpipe.METRICS`` established) — a bare module-level mutable
container in the per-node subsystems silently shares one node's state
with the whole fleet.  This pass flags module-level mutable containers
and stateful singletons in those subsystems unless they are Routers or
explicitly registered in place with a reasoned disable comment::

    PUBKEYS = PubkeyCache()   # speclint: disable=global-mutable-state -- ...

The comment is the registration: it forces every new global to carry a
written argument for why sharing it across SimNodes is sound.
"""
from __future__ import annotations

import ast

from .core import Context, Finding

_SCOPE = (
    "consensus_specs_tpu.resilience",
    "consensus_specs_tpu.sigpipe",
    "consensus_specs_tpu.gossip",
    "consensus_specs_tpu.txn",
    "consensus_specs_tpu.scenario",
)

_MUTABLE_BUILTINS = frozenset({
    "dict", "list", "set", "bytearray", "deque", "defaultdict",
    "OrderedDict", "Counter", "ChainMap", "local",
})

# stateful registry classes this repo defines; instantiating one at
# module level creates fleet-shared state
_STATEFUL_CLASSES = frozenset({
    "Metrics", "IncidentLog", "PubkeyCache", "AggregatePubkeyCache",
    "Supervisor", "DifferentialGuard", "TxnManager", "Journal",
    "AdmissionPipeline", "IncrementalTracker",
})

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.SetComp, ast.DictComp)


def _callee(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _mutable_reason(value: ast.expr) -> str | None:
    if isinstance(value, _MUTABLE_LITERALS):
        return "a mutable container literal"
    if isinstance(value, ast.Call):
        name = _callee(value.func)
        if name in ("Router", "StateRouter"):
            return None                     # the sanctioned patterns:
            # attribute-delegating Router (INCIDENTS/METRICS) and the
            # optional-singleton StateRouter (supervisor/plan/guard)
        if name in _MUTABLE_BUILTINS:
            return f"a mutable {name}()"
        if name in _STATEFUL_CLASSES:
            return f"a stateful {name} singleton"
    return None


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for sf in ctx.files:
        if not sf.in_module(*_SCOPE):
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            else:
                continue
            reason = _mutable_reason(value)
            if reason is None:
                continue
            names = ", ".join(t.id for t in targets
                              if isinstance(t, ast.Name)) or "<target>"
            if names.startswith("__") and names.endswith("__"):
                continue        # __all__ and friends: interpreter protocol

            findings.append(Finding(
                "global-mutable-state", sf.rel, node.lineno,
                node.col_offset,
                f"module-level {names} is {reason} — fleet-shared state "
                f"in a per-node subsystem",
                hint="wrap it in utils.nodectx.Router, make it "
                     "immutable, or register it in place: `# speclint: "
                     "disable=global-mutable-state -- <why sharing "
                     "across nodes is sound>`"))
    return findings
