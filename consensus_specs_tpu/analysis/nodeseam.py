"""Pass 12 — node scalar-bypass gate.

The front-door node (node/) serves traffic by FEEDING the gossip
`AdmissionPipeline` — verification rides the pipeline's registered
seams (micro-batched device verify, ``scalar_only`` as the counted
degradation mode).  Node code that imports the scalar `crypto.*`
suite directly, or calls a scalar oracle verb by name, verifies
traffic outside the pipeline's breaker/fallback/counting contract —
the overload watermark, the degraded-mode metrics, and the drill's
byte-identity argument all stop describing the process.

Same shape as the ``factory-scalar-bypass`` pass: inside
``consensus_specs_tpu.node`` modules only, flag any import of
``consensus_specs_tpu.crypto.*`` and any call whose terminal name is
a scalar oracle verb.  A deliberate exception carries
``# speclint: disable=node-scalar-bypass -- <reason>``.
"""
from __future__ import annotations

import ast

from .core import Context, Finding
from .factoryseam import _SCALAR_CALLS, _resolved_import

_SCOPE = ("consensus_specs_tpu.node", "consensus_specs_tpu.mesh")
_CRYPTO = "consensus_specs_tpu.crypto"


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for sf in ctx.files:
        if not sf.in_module(*_SCOPE):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == _CRYPTO or \
                            alias.name.startswith(_CRYPTO + "."):
                        findings.append(_import_finding(sf, node))
            elif isinstance(node, ast.ImportFrom):
                mod = _resolved_import(sf, node)
                if mod == _CRYPTO or mod.startswith(_CRYPTO + "."):
                    findings.append(_import_finding(sf, node))
            elif isinstance(node, ast.Call):
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else None)
                if name in _SCALAR_CALLS:
                    findings.append(Finding(
                        "node-scalar-bypass", sf.rel, node.lineno,
                        node.col_offset,
                        f"node code calls the scalar oracle verb "
                        f"{name}() directly — traffic verifies outside "
                        f"the admission pipeline's counted seams",
                        hint="submit through the AdmissionPipeline "
                             "(scalar_only is its counted degradation "
                             "mode) or carry a reasoned disable"))
    return findings


def _import_finding(sf, node) -> Finding:
    return Finding(
        "node-scalar-bypass", sf.rel, node.lineno, node.col_offset,
        "node code imports the scalar crypto suite directly — the "
        "front door verifies only through the admission pipeline's "
        "registered seams",
        hint="feed the AdmissionPipeline instead, or carry a "
             "reasoned disable")
