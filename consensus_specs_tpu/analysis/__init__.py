"""speclint: AST-based machine enforcement of the repo's cross-cutting
safety contracts.

Seven PRs built the safety story on conventions — every accelerator
entry point behind ``resilience.dispatch(site, device_fn, fallback_fn)``,
every seam chaos-covered and documented, injected clocks instead of
wall time, per-node routed globals, store mutation only inside
``@transactional`` seams.  This package turns each convention into a
lint pass over the whole package (stdlib ``ast`` only — no jax, no
heavy imports, < 10 s for the full tree), anchored on the canonical
site registry ``resilience/sites.py``:

* seams.py        — every dispatch/fire/FaultSpec site registered, every
                    dispatch passes a fallback, registry live + documented.
* bypass.py       — device kernels only importable from registered
                    wrapper modules.
* determinism.py  — no wall clock / unseeded RNG in the replayable
                    subsystems (sigpipe, gossip, txn, scenario, ssz).
* globals_.py     — module-level mutable state in per-node subsystems
                    must be a nodectx Router or registered with a reason.
* txnpurity.py    — store writes only in (or under) @transactional
                    handlers.
* hostsync.py     — host-sync primitives only inside declared join
                    barriers (the async-flush re-serialization gate).
* concurrency.py  — lock discipline (guarded attrs only under their
                    registered lock), lock order (static acquisition
                    graph must be acyclic), thread escape (worker-role
                    mutations lock-guarded or via registered handoffs);
                    anchored on the CONCURRENCY registry and paired
                    with the SPECLINT_TSAN runtime tracer
                    (utils/locks.py).
* foldgate.py     — pairing_product reachable only through the seam
                    registry's fold-aware entry (sigpipe.scheduler /
                    the ops.pairing_fold seam), so nothing quietly
                    re-introduces an unfolded 2N-leg product.

Entry points: :func:`run_speclint` (library), ``scripts/speclint.py``
(CLI, JSON or human output, ``--pass``/``--list-passes`` filters, exit
1 on findings), ``make speclint`` / ``make test-quick`` (CI gate),
tests/test_speclint.py (pytest gate).  Rule catalogue and escape-hatch
policy: docs/analysis.md.
"""
from .core import RULES, Finding, load_context, pass_names, run_speclint

__all__ = ["Finding", "RULES", "load_context", "pass_names",
           "run_speclint"]
