"""Pass 2 — seam bypass.

The device kernels (``consensus_specs_tpu.ops.*`` and the native C++
bindings) must only be reached through a registered dispatch wrapper:
the wrapper is where the circuit breaker, the watchdog, the fault
injector, and the differential guard live, so a direct import anywhere
else is an accelerator call that no chaos schedule can kill and no
breaker can trip.  The allowed importers are derived from the site
registry (every ``Site.module``) plus the explicitly-registered
kernel-layer packages below.
"""
from __future__ import annotations

import ast

from .core import Context, Finding, SourceFile

_KERNEL_PREFIXES = (
    "consensus_specs_tpu.ops",
    "consensus_specs_tpu.native",
)

# kernel-layer packages/modules that ARE the device side (importing a
# kernel there is implementing the seam, not bypassing it)
_KERNEL_LAYER = (
    "consensus_specs_tpu.ops",          # the kernels themselves
    "consensus_specs_tpu.native",       # C++ host-tier bindings
    "consensus_specs_tpu.parallel",     # mesh engine: multi-chip device layer
    "consensus_specs_tpu.ssz.impl",     # backend selector: installs the
                                        # level hasher behind merkle's seam
    "consensus_specs_tpu.gen",          # offline conformance-vector
                                        # tooling, not node runtime
)


def _is_kernel(module: str) -> bool:
    return any(module == p or module.startswith(p + ".")
               for p in _KERNEL_PREFIXES)


def _allowed(sf_module: str, wrappers: frozenset[str]) -> bool:
    if sf_module in wrappers:
        return True
    return any(sf_module == p or sf_module.startswith(p + ".")
               for p in _KERNEL_LAYER)


def _absolute(sf: SourceFile, node: ast.ImportFrom) -> str:
    """Resolve a (possibly relative) from-import to a dotted module."""
    if node.level == 0:
        return node.module or ""
    pkg = sf.module.split(".") if sf.module else []
    if not sf.is_package and pkg:
        pkg = pkg[:-1]
    if node.level > 1:
        pkg = pkg[:len(pkg) - (node.level - 1)]
    return ".".join(pkg + (node.module.split(".") if node.module else []))


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    wrappers = ctx.registry.wrapper_modules()
    for sf in ctx.files:
        if not (sf.module or sf.forced):
            continue            # tests/scripts may drive kernels directly
        if sf.module and _allowed(sf.module, wrappers):
            continue
        for node in ast.walk(sf.tree):
            targets = []
            if isinstance(node, ast.Import):
                targets = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                mod = _absolute(sf, node)
                # `from ..ops import msm` names the kernel in the alias
                targets = [mod] + [f"{mod}.{a.name}" for a in node.names]
            for mod in targets:
                if _is_kernel(mod):
                    findings.append(Finding(
                        "bypass-direct-kernel", sf.rel, node.lineno,
                        node.col_offset,
                        f"direct device-kernel import {mod!r} outside a "
                        f"registered dispatch wrapper",
                        hint="route the call through resilience.dispatch "
                             "in a wrapper module registered in "
                             "resilience/sites.py"))
                    break
    return findings
