"""Pass 10 — fold-aware pairing-product gate.

The folded verify path (sigpipe/fold.py, the ``ops.pairing_fold``
seam) owns the decision of how a fused flush's pairing legs are
assembled: N+1 folded legs by default, the 2N-leg assembly behind the
``FOLD_VERIFY=0`` escape hatch, and the one-launch fused program on
the tpu backend.  A caller that reaches ``pairing_product`` directly —
instead of going through the scheduler's fold-aware entry
(``sigpipe.scheduler._pairing_product``) or the fold seam itself —
silently re-introduces an unfolded 2N-leg product (or worse, a product
that skips the seam registry's breaker/bisect/fallback contract), and
every counted invariant (`miller_loops_per_flush`) stops describing
what actually launched.

This pass flags any ``pairing_product(...)`` call in the package
outside the modules the seam registry blesses: the wrapper modules of
``ops.pairing_product`` and ``ops.pairing_fold`` (the owning layers)
and ``sigpipe.scheduler`` (the fold-aware router).  Like every pass,
``# speclint: disable=fold-unaware-pairing -- <reason>`` is the escape
hatch for a deliberate exception.
"""
from __future__ import annotations

import ast

from .core import Context, Finding

_ROUTER = "consensus_specs_tpu.sigpipe.scheduler"


def _allowed_modules(registry) -> frozenset:
    allowed = {_ROUTER}
    for name in ("ops.pairing_product", "ops.pairing_fold"):
        try:
            allowed.add(registry.site(name).module)
        except KeyError:
            pass
    return frozenset(allowed)


def run(ctx: Context) -> list[Finding]:
    allowed = _allowed_modules(ctx.registry)
    findings: list[Finding] = []
    for sf in ctx.files:
        if not (sf.module or sf.forced):
            continue
        if sf.module in allowed:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None)
            if name != "pairing_product":
                continue
            findings.append(Finding(
                "fold-unaware-pairing", sf.rel, node.lineno,
                node.col_offset,
                "pairing_product() called outside the seam registry's "
                "fold-aware entry — the folded N+1-leg assembly (and "
                "the FOLD_VERIFY escape hatch) is bypassed",
                hint="route the product through sigpipe.scheduler."
                     "_pairing_product / the ops.pairing_fold seam, or "
                     "carry a reasoned disable"))
    return findings
