"""Pass 13 — epoch scalar-bypass gate.

Epoch processing's whole contract (specs/epoch_fast.py) is ONE
registered ``ops.epoch_sweep`` dispatch per ``process_epoch``: the
breaker, the watchdog, the fault injector, the lane guard and the
``epoch_sweep_*`` counters all live at that seam.  Package code that
imports the device program (``ops/epoch_sweep.py``) directly, or
reaches the wrapper's array internals (``StateArrays``,
``numpy_sweep``, the mask builders, the writeback helpers), runs epoch
math on a path no chaos schedule can kill, no breaker can trip, and no
counter records — the one-dispatch pin silently stops describing the
engine.

This pass flags, inside ``consensus_specs_tpu.*`` (tests and bench.py
sit outside the package and drive internals deliberately):

* any import of ``consensus_specs_tpu.ops.epoch_sweep`` outside its
  sole registered wrapper ``specs.epoch_fast`` — tighter than the
  generic ``bypass-direct-kernel`` gate, which allows ANY wrapper
  module to import ANY kernel;
* any ``from ...epoch_fast import <name>`` or ``epoch_fast.<name>``
  access whose name is not the wrapper's public surface
  (``ENABLED`` / ``SWEEP_SITE`` / ``scalar_epoch`` / ``fused_epoch`` /
  ``set_guard``).

A deliberate exception carries
``# speclint: disable=epoch-scalar-bypass -- <reason>``.
"""
from __future__ import annotations

import ast

from .core import Context, Finding, SourceFile

_WRAPPER = "consensus_specs_tpu.specs.epoch_fast"
_DEVICE = "consensus_specs_tpu.ops.epoch_sweep"

# the wrapper's whole public surface; everything else is engine-internal
_ALLOWED = frozenset({
    "ENABLED", "SWEEP_SITE", "scalar_epoch", "fused_epoch", "set_guard",
})


def _absolute(sf: SourceFile, node: ast.ImportFrom) -> str:
    """Resolve a (possibly relative) from-import to a dotted module."""
    if node.level == 0:
        return node.module or ""
    pkg = sf.module.split(".") if sf.module else []
    if not sf.is_package and pkg:
        pkg = pkg[:-1]
    if node.level > 1:
        pkg = pkg[:len(pkg) - (node.level - 1)]
    return ".".join(pkg + (node.module.split(".") if node.module else []))


def _device_finding(sf: SourceFile, node: ast.AST) -> Finding:
    return Finding(
        "epoch-scalar-bypass", sf.rel, node.lineno, node.col_offset,
        "direct import of the fused epoch device program "
        "(ops.epoch_sweep) outside its registered wrapper "
        "specs.epoch_fast",
        hint="go through epoch_fast.fused_epoch — the ops.epoch_sweep "
             "dispatch seam owns the breaker/guard/counter contract")


def _surface_finding(sf: SourceFile, node: ast.AST, name: str) -> Finding:
    return Finding(
        "epoch-scalar-bypass", sf.rel, node.lineno, node.col_offset,
        f"epoch_fast.{name} is engine-internal — epoch array math "
        f"outside the seam runs unsupervised and uncounted",
        hint="use the public surface (ENABLED, SWEEP_SITE, "
             "scalar_epoch, fused_epoch, set_guard) or carry a "
             "reasoned disable")


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for sf in ctx.files:
        if sf.module == _WRAPPER:
            continue            # the wrapper IS the seam implementation
        if not (sf.module or sf.forced):
            continue            # tests/bench drive internals deliberately
        aliases: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == _DEVICE or \
                            a.name.startswith(_DEVICE + "."):
                        findings.append(_device_finding(sf, node))
                    elif a.name == _WRAPPER and a.asname:
                        aliases.add(a.asname)
            elif isinstance(node, ast.ImportFrom):
                mod = _absolute(sf, node)
                if mod == _DEVICE or mod.startswith(_DEVICE + "."):
                    findings.append(_device_finding(sf, node))
                    continue
                for a in node.names:
                    if f"{mod}.{a.name}" == _DEVICE:
                        findings.append(_device_finding(sf, node))
                    elif f"{mod}.{a.name}" == _WRAPPER:
                        aliases.add(a.asname or a.name)
                    elif mod == _WRAPPER and a.name not in _ALLOWED:
                        findings.append(
                            _surface_finding(sf, node, a.name))
        if not aliases:
            continue
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in aliases
                    and node.attr not in _ALLOWED
                    and not node.attr.startswith("__")):
                findings.append(_surface_finding(sf, node, node.attr))
    return findings
