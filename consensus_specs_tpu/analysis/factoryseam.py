"""Pass 11 — factory scalar-bypass gate.

The vector factory's whole bargain (factory/engine.py) is that
generation-time BLS / KZG / merkle work rides the registered seams —
the sigpipe fused flush, the ``ops.pairing_fold`` fold, the incremental
merkle sweep — with the scalar oracle reachable only as a seam's
counted fallback.  Factory code that imports the scalar `crypto.*`
suite directly, or calls a scalar oracle verb by name, silently moves
generation work off the engines: the bench's device-vs-scalar split
stops describing the service, and the seam registry's
breaker/fallback/counting contract no longer covers the call.

This pass flags, inside ``consensus_specs_tpu.factory`` modules only:

* any import of ``consensus_specs_tpu.crypto.*`` (absolute or
  relative) — the scalar suite is the engines' fallback, not a factory
  dependency;
* any call whose terminal name is a scalar oracle verb
  (``Verify`` / ``FastAggregateVerify`` / ``pairing_check`` /
  ``hash_to_g2`` / the KZG verify verbs / ...).

Case fns whose *vector content* is a scalar oracle result (the `bls`
runner's own Verify cases) live in `gen/` and `spec_tests/`, outside
this scope — the factory invokes them through `gen.runner._write_case`,
which is the point.  A deliberate exception inside the factory carries
``# speclint: disable=factory-scalar-bypass -- <reason>``.
"""
from __future__ import annotations

import ast

from .core import Context, Finding

_SCOPE = ("consensus_specs_tpu.factory",)
_CRYPTO = "consensus_specs_tpu.crypto"

# terminal call names that ARE the scalar oracle surface
_SCALAR_CALLS = frozenset({
    "Verify", "AggregateVerify", "FastAggregateVerify", "Sign",
    "KeyValidate", "Aggregate", "AggregatePKs", "pairing_check",
    "multi_exp", "hash_to_g2", "verify_kzg_proof",
    "verify_blob_kzg_proof", "verify_blob_kzg_proof_batch",
    "verify_kzg_proof_batch", "compute_kzg_proof",
})


def _resolved_import(sf, node) -> str:
    """The dotted module an Import/ImportFrom reaches (best effort for
    relative imports; '' when unresolvable)."""
    if isinstance(node, ast.Import):
        return ""               # handled per-alias by the caller
    base = sf.module.split(".") if sf.module else []
    if node.level:
        if len(base) < node.level:
            return node.module or ""
        base = base[:len(base) - node.level]
    else:
        base = []
    return ".".join(base + ([node.module] if node.module else []))


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for sf in ctx.files:
        if not sf.in_module(*_SCOPE):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == _CRYPTO or \
                            alias.name.startswith(_CRYPTO + "."):
                        findings.append(_import_finding(sf, node))
            elif isinstance(node, ast.ImportFrom):
                mod = _resolved_import(sf, node)
                if mod == _CRYPTO or mod.startswith(_CRYPTO + "."):
                    findings.append(_import_finding(sf, node))
            elif isinstance(node, ast.Call):
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else None)
                if name in _SCALAR_CALLS:
                    findings.append(Finding(
                        "factory-scalar-bypass", sf.rel, node.lineno,
                        node.col_offset,
                        f"factory code calls the scalar oracle verb "
                        f"{name}() directly — generation work moves off "
                        f"the registered engines uncounted",
                        hint="route through the sigpipe / ops seams "
                             "(factory/engine.py arms them) or carry a "
                             "reasoned disable"))
    return findings


def _import_finding(sf, node) -> Finding:
    return Finding(
        "factory-scalar-bypass", sf.rel, node.lineno, node.col_offset,
        "factory code imports the scalar crypto suite directly — the "
        "scalar path is a seam's counted fallback, not a factory "
        "dependency",
        hint="generate through gen.runner case fns with the engines "
             "armed (factory/engine.py), or carry a reasoned disable")
