"""Pass 6 — host-sync points (the async-flush re-serialization gate).

The async pipelined flush engine (sigpipe/pipeline_async.py) hides
host-side planning under device work by keeping every dispatch's result
un-forced until a DECLARED join barrier.  One stray
``jax.device_get(...)`` / ``.block_until_ready()`` / ``np.asarray(...)``
on a device value in the middle of a dispatch chain silently
re-serializes the whole pipeline — the code still passes every parity
test, it just stops overlapping, which is exactly the kind of
regression only a machine check catches.

This pass flags the host-sync primitives in the pipelined packages
(``sigpipe``, ``ssz``, ``parallel``) unless they sit inside a function
registered as a join barrier in ``resilience/sites.py
HOST_SYNC_BARRIERS`` (the same canonical-registry discipline as the
dispatch seams: adding a barrier means adding a registry row, and the
row obliges the function's docstring to say what join it is).

``np.asarray`` is flagged because it is how device values are forced in
this codebase's numpy-bridge idiom; a *host-side* ``np.asarray`` in
these packages should live behind a registered barrier function or, if
genuinely device-free, carry an inline ``# speclint:
disable=async-host-sync -- <why this never touches a device value>``.
"""
from __future__ import annotations

import ast

from .core import Context, Finding

_SCOPE = (
    "consensus_specs_tpu.sigpipe",
    "consensus_specs_tpu.ssz",
    "consensus_specs_tpu.parallel",
)

# dotted call names that force a device value back to the host
_SYNC_CALLS = frozenset({
    "jax.device_get", "np.asarray", "numpy.asarray", "onp.asarray",
})


def _dotted(expr: ast.expr) -> str | None:
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _alias_map(tree: ast.AST) -> dict:
    """local name -> canonical prefix for jax / numpy imports, so
    `import numpy as anything` or `from jax import device_get` cannot
    dodge the gate."""
    aliases: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root in ("jax", "numpy"):
                    aliases[(a.asname or a.name).split(".")[0]] = root
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and \
                node.module and node.module.split(".")[0] in \
                ("jax", "numpy"):
            for a in node.names:
                aliases[a.asname or a.name] = \
                    f"{node.module.split('.')[0]}.{a.name}"
    return aliases


def _canonical(name: str, aliases: dict) -> str:
    head, _, tail = name.partition(".")
    mapped = aliases.get(head)
    if mapped is None:
        return name
    return f"{mapped}.{tail}" if tail else mapped


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf, module: str, barriers: frozenset,
                 aliases: dict, findings: list):
        self.sf = sf
        self.module = module
        self.barriers = barriers
        self.aliases = aliases
        self.findings = findings
        self.stack: list = []       # enclosing function names

    def _in_barrier(self) -> bool:
        return any((self.module, name) in self.barriers
                   for name in self.stack)

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        self.generic_visit(node)
        if self._in_barrier():
            return
        name = _dotted(node.func)
        if name is None:
            return
        if name.endswith(".block_until_ready") or \
                name == "block_until_ready":
            self._flag(node, "block_until_ready()")
            return
        canon = _canonical(name, self.aliases)
        # numpy.asarray in any spelling (np.asarray, onp.asarray, a
        # from-import) and jax.device_get in any spelling
        if canon in _SYNC_CALLS or canon == "numpy.asarray" \
                or canon == "jax.device_get":
            self._flag(node, f"{name}()")

    def _flag(self, node, what: str) -> None:
        self.findings.append(Finding(
            "async-host-sync", self.sf.rel, node.lineno, node.col_offset,
            f"{what} forces a device value outside a declared join "
            f"barrier — this re-serializes the async flush pipeline",
            hint="move the forced read into a registered barrier "
                 "function (resilience/sites.py HOST_SYNC_BARRIERS) or "
                 "register this one; a genuinely device-free asarray "
                 "may carry a reasoned disable"))


def run(ctx: Context) -> list[Finding]:
    barriers = frozenset(getattr(ctx.registry, "HOST_SYNC_BARRIERS", ()))
    findings: list[Finding] = []
    for sf in ctx.files:
        if not sf.in_module(*_SCOPE):
            continue
        aliases = _alias_map(sf.tree)
        v = _Visitor(sf, sf.module, barriers, aliases, findings)
        v.visit(sf.tree)
    return findings
