"""Pass 1 — seam conformance.

Every site-bearing call (``dispatch``/``_dispatch``/``_stub_or_dispatch``
at the accelerator seams, ``fire`` at the transactional barriers,
``FaultSpec`` in chaos schedules) must name a site registered in
resilience/sites.py, dispatch calls must pass a fallback, and the
registry itself must be live: every registered site used somewhere,
every registered site in its doc's site table.  Chaos reachability is
enforced structurally — the chaos tuples derive from the registry, and
UNIT-tier entries must cite their covering suite (sites.py raises at
import otherwise) — so the drift this pass hunts is call-site drift:
the first bypassed kernel or misspelled site name fails the lint.
"""
from __future__ import annotations

import ast

from .core import Context, Finding, SourceFile

# call name -> (site argument index, minimum args for a fallback; None =
# the call shape carries no fallback obligation)
_SEAM_CALLS: dict[str, tuple[int, int | None]] = {
    "dispatch": (0, 3),
    "_dispatch": (0, 3),
    "_stub_or_dispatch": (0, 4),
    "fire": (0, None),
    "FaultSpec": (0, None),
}

_REGISTER_HINT = ("register the seam in consensus_specs_tpu/resilience/"
                  "sites.py (one Site entry + a docs/resilience.md row)")


def _call_name(func: ast.expr) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


class _ModuleConstants(ast.NodeVisitor):
    """Module-level ``NAME = <resolvable site string>`` bindings, plus
    names imported from resilience.sites."""

    def __init__(self, sf: SourceFile, registry):
        self.values: dict[str, str] = {}
        self.registry = registry
        for node in sf.tree.body:
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.split(".")[-1] == "sites":
                for alias in node.names:
                    v = getattr(registry, alias.name, None)
                    if isinstance(v, str):
                        self.values[alias.asname or alias.name] = v
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                v = self._value(node.value)
                if v is not None:
                    self.values[node.targets[0].id] = v

    def _value(self, expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            return self.values.get(expr.id)
        # the registry-derived idiom: sites.site("x").name
        if isinstance(expr, ast.Attribute) and expr.attr == "name" and \
                isinstance(expr.value, ast.Call):
            call = expr.value
            if _call_name(call.func) == "site" and call.args and \
                    isinstance(call.args[0], ast.Constant) and \
                    isinstance(call.args[0].value, str):
                return call.args[0].value
        return None


class _SeamVisitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, consts: _ModuleConstants,
                 registry, findings: list[Finding], used: set[str]):
        self.sf = sf
        self.consts = consts
        self.registry = registry
        self.findings = findings
        self.used = used
        self._params: list[set[str]] = []

    def visit_FunctionDef(self, node):
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node)

    def _visit_func(self, node):
        a = node.args
        params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        if a.vararg:
            params.add(a.vararg.arg)
        if a.kwarg:
            params.add(a.kwarg.arg)
        self._params.append(params)
        self.generic_visit(node)
        self._params.pop()

    def _is_param(self, name: str) -> bool:
        return any(name in scope for scope in self._params)

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        name = _call_name(node.func)
        shape = _SEAM_CALLS.get(name or "")
        if shape is None:
            return
        site_idx, min_args = shape
        site_expr = None
        if len(node.args) > site_idx:
            site_expr = node.args[site_idx]
        else:
            for kw in node.keywords:
                if kw.arg == "site":
                    site_expr = kw.value
        if site_expr is None:
            return
        resolved = self._resolve(site_expr)
        if resolved is None:
            if not (isinstance(site_expr, ast.Name)
                    and self._is_param(site_expr.id)):
                # forwarding wrappers (`def _dispatch(site, ...)`) are
                # checked at THEIR call sites; anything else dynamic is
                # unverifiable and flagged
                self.findings.append(Finding(
                    "seam-dynamic-site", self.sf.rel, site_expr.lineno,
                    site_expr.col_offset,
                    f"{name}() site argument is not statically "
                    f"resolvable to a registered site name",
                    hint="use a string literal or a module constant "
                         "derived from resilience/sites.py"))
        else:
            self.used.add(resolved)
            if not self.registry.is_registered(resolved):
                self.findings.append(Finding(
                    "seam-unregistered-site", self.sf.rel,
                    site_expr.lineno, site_expr.col_offset,
                    f"{name}() names unregistered site {resolved!r}",
                    hint=_REGISTER_HINT))
        if min_args is not None:
            # _stub_or_dispatch names its fallback parameter native_fn
            has_fallback = (len(node.args) >= min_args
                            or any(kw.arg in ("fallback_fn", "native_fn")
                                   for kw in node.keywords))
            if not has_fallback:
                self.findings.append(Finding(
                    "seam-missing-fallback", self.sf.rel, node.lineno,
                    node.col_offset,
                    f"{name}() call passes no fallback_fn — the seam "
                    f"contract is dispatch(site, device_fn, fallback_fn)",
                    hint="the fallback must be the byte-identical "
                         "native-oracle path"))

    def _resolve(self, expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name) and not self._is_param(expr.id):
            return self.consts.values.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            # sites.SOME_CONSTANT / registry-module attribute access
            v = getattr(self.registry, expr.attr, None)
            if isinstance(v, str) and expr.value.id in (
                    "sites", "site_registry"):
                return v
        return None


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    used: set[str] = set()
    fixture_mode = bool(ctx.files) and all(sf.forced for sf in ctx.files)
    for sf in ctx.files:
        if not (sf.module or sf.forced or sf.rel.endswith("test_chaos.py")):
            continue
        consts = _ModuleConstants(sf, ctx.registry)
        _SeamVisitor(sf, consts, ctx.registry, findings, used).visit(sf.tree)
    if fixture_mode:
        return findings
    # registry liveness: every site used, every site documented
    sites_rel = "consensus_specs_tpu/resilience/sites.py"
    sites_text = (ctx.root / sites_rel).read_text().splitlines()

    def _decl_line(name: str) -> int:
        needle = f'"{name}"'
        for i, line in enumerate(sites_text, start=1):
            if needle in line:
                return i
        return 1

    doc_cache: dict[str, frozenset[str]] = {}
    from .registry import documented_sites
    for s in ctx.registry.REGISTRY:
        if s.name not in used:
            findings.append(Finding(
                "site-unused", sites_rel, _decl_line(s.name), 0,
                f"registered site {s.name!r} has no dispatch/fire call "
                f"site in the package",
                hint="delete the registration or wire the seam"))
        if s.doc not in doc_cache:
            doc_cache[s.doc] = documented_sites(ctx.root, s.doc)
        if s.name not in doc_cache[s.doc]:
            findings.append(Finding(
                "site-undocumented", sites_rel, _decl_line(s.name), 0,
                f"registered site {s.name!r} is missing from the "
                f"{s.doc} site table",
                hint=f"add a `{s.name}` row describing the device path "
                     f"and fallback"))
    return findings
