"""Passes 7-9 — concurrency: lock discipline, lock order, thread escape.

PR 11 made the hot path genuinely multi-threaded (engine worker, leg
worker, gossip drainer, watchdog workers) with ~20 lock sites across
sigpipe/gossip/txn/resilience — and the overlap contracts were enforced
only by tests that happen to race.  These passes check the source
against the ``CONCURRENCY`` registry in ``resilience/sites.py`` (locks
with the attribute sets they guard, thread roles, sanctioned
cross-thread handoffs), the same declare-once discipline the seam
passes apply to dispatch sites:

* **lock-discipline** (``conc-unguarded-attr`` / ``conc-unregistered-
  lock`` / ``registry-dead-entry``) — an attribute a registered lock
  guards may be read or written only while that lock is held: lexically
  inside ``with <lock>`` (or after an explicit ``.acquire()`` in the
  same function), or in a function the package-wide name-union call
  graph shows is invoked from under the lock (the txn-purity pass's
  reachability idiom — over-approximate on purpose: a helper called
  from both locked and unlocked contexts is assumed locked, and the
  runtime tracer covers what static analysis must guess).  Bare
  ``threading.Lock/RLock/Condition`` constructions in the concurrency-
  scoped packages are findings — locks are built via
  ``utils/locks.py`` named constructors so the SPECLINT_TSAN tracer
  can see them — and every registry entry (locks, roles, handoffs,
  HOST_SYNC_BARRIERS) must resolve to real code.
* **lock-order** (``conc-lock-order-cycle``) — the static lock-
  acquisition graph: holding A while acquiring B (lexically nested
  ``with``s, or a call under A to a function whose call-graph closure
  acquires B) adds edge A->B.  Any cycle is a potential deadlock; a
  lexical self-edge on a non-reentrant ``lock`` kind is a guaranteed
  one.  The same graph is what the runtime
  :class:`utils.locks.LockTracer` checks observed acquisition
  sequences against.
* **thread-escape** (``conc-thread-escape``) — state mutated from a
  registered worker role's entry point (within its own module, over
  the in-module call closure) must be lock-guarded, a registered
  cross-thread handoff, or thread-local.  This is exactly the contract
  per-node async needs before the nodectx breaker table can be
  namespaced: a worker that scribbles on unguarded shared state cannot
  be fenced into a node.

Scope: ``sigpipe``, ``gossip``, ``txn``, ``resilience``, ``scenario``
and ``utils`` (minus ``utils/locks.py`` itself, which IS the
primitive layer).  Like every pass: stdlib ``ast`` only.
"""
from __future__ import annotations

import ast

from .core import Context, Finding

_SCOPE = (
    "consensus_specs_tpu.sigpipe",
    "consensus_specs_tpu.gossip",
    "consensus_specs_tpu.txn",
    "consensus_specs_tpu.resilience",
    "consensus_specs_tpu.scenario",
    "consensus_specs_tpu.utils",
    "consensus_specs_tpu.node",
    "consensus_specs_tpu.mesh",
)

# the primitive layer: the one module allowed to touch threading locks
_EXEMPT_MODULES = ("consensus_specs_tpu.utils.locks",)

_NAMED_CTORS = frozenset({"named_lock", "named_rlock", "named_condition"})
_RAW_CTORS = frozenset({"Lock", "RLock", "Condition"})

# in-place mutator method names (the txn-purity set)
_MUTATORS = frozenset({
    "append", "add", "update", "pop", "clear", "extend", "insert",
    "setdefault", "remove", "discard", "popitem",
})


def _in_scope(sf) -> bool:
    if sf.module in _EXEMPT_MODULES:
        return False
    return sf.in_module(*_SCOPE)


def _called_names(fn) -> set:
    """Direct callees resolvable by name: bare-name calls and
    self/cls-method calls (the txn-purity resolution rule — ubiquitous
    dict/list method names on arbitrary bases are deliberately NOT
    resolved, they would wire the graph into spaghetti)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls"):
                out.add(f.attr)
    return out


def _root_name(expr):
    """The base Name of an attribute/subscript chain, plus the
    outermost attribute directly on it ('' for the bare Name)."""
    attr = ""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id, attr
    return None, attr


class _FnInfo:
    __slots__ = ("sf", "node", "name", "cls", "calls", "acquires",
                 "accesses", "mutations", "calls_under", "edges")

    def __init__(self, sf, node, cls):
        self.sf = sf
        self.node = node
        self.name = node.name
        self.cls = cls
        self.calls = _called_names(node)
        self.acquires: set = set()       # lock names acquired anywhere
        self.accesses: list = []         # (attr, kind, held, line, col)
        self.mutations: list = []        # (root, name, held, line, col)
        self.calls_under: dict = {}      # lock name -> called names
        self.edges: set = set()          # lexical (outer, inner) pairs


class _FnWalker:
    """Walks one function body tracking the lexically-held lock set."""

    def __init__(self, model, info):
        self.m = model
        self.info = info
        self.held: list = []            # lock names, outer first
        self.rest: set = set()          # .acquire()-style, rest-of-fn

    def _held_set(self):
        return frozenset(self.held) | frozenset(self.rest)

    def walk(self):
        for stmt in self.info.node.body:
            self._stmt(stmt)

    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                      # nested defs walked separately
        if isinstance(node, (ast.With, ast.AsyncWith)):
            names = []
            for item in node.items:
                self._expr(item.context_expr)
                spec = self.m.lock_of(item.context_expr, self.info)
                if spec is not None:
                    self._note_acquire(spec.name)
                    names.append(spec.name)
                    # held immediately: `with A, B:` acquires A first,
                    # so B's acquisition must see A on the stack or the
                    # order pass misses cycles written in one statement
                    self.held.append(spec.name)
            for stmt in node.body:
                self._stmt(stmt)
            for _ in names:
                self.held.pop()
            return
        self._targets(node)
        self._children(node)

    def _children(self, node):
        """Dispatch every AST child: statements re-enter _stmt (so
        nested withs stack), expressions go to _expr, anything else
        (except handlers, match cases) recurses field-wise."""
        for _field, value in ast.iter_fields(node):
            for child in (value if isinstance(value, list) else [value]):
                if isinstance(child, ast.stmt):
                    self._stmt(child)
                elif isinstance(child, ast.expr):
                    self._expr(child)
                elif isinstance(child, ast.AST):
                    self._children(child)

    def _note_acquire(self, name: str) -> None:
        info = self.info
        info.acquires.add(name)
        for outer in self._held_set():
            info.edges.add((outer, name))

    def _targets(self, node):
        """Record mutations for the thread-escape pass."""
        targets: list = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        work = list(targets)    # a worklist COPY: extending the live
        #                         node.targets would corrupt the shared
        #                         AST every other pass re-walks
        while work:
            t = work.pop()
            if isinstance(t, ast.Tuple):
                work.extend(t.elts)
                continue
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                root, attr = _root_name(t)
                if root == "self" and attr:
                    self.info.mutations.append(
                        ("self", attr, self._held_set(),
                         t.lineno, t.col_offset))
                elif root in self.m.module_globals.get(self.info.sf.rel,
                                                       ()):
                    self.info.mutations.append(
                        ("global", root, self._held_set(),
                         t.lineno, t.col_offset))
            elif isinstance(t, ast.Name) and \
                    t.id in self._declared_globals():
                self.info.mutations.append(
                    ("global", t.id, self._held_set(),
                     t.lineno, t.col_offset))

    def _declared_globals(self):
        return self.m.fn_globals.get(id(self.info.node), frozenset())

    def _expr(self, node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._call(sub)
            elif isinstance(sub, ast.Attribute):
                self._access(sub.attr, sub.lineno, sub.col_offset)
            elif isinstance(sub, ast.Name):
                self._name(sub)

    def _call(self, node):
        f = node.func
        # lock.acquire(): held for the rest of the function (the
        # try/finally-release idiom the gossip drainer uses)
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            spec = self.m.lock_of(f.value, self.info)
            if spec is not None:
                self._note_acquire(spec.name)
                self.rest.add(spec.name)
                return
        # calls made while holding a lock (interprocedural order edges
        # + the under-lock reachability seeds)
        held = self._held_set()
        if held:
            callee = None
            if isinstance(f, ast.Name):
                callee = f.id
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls"):
                callee = f.attr
            if callee is not None:
                for lock in held:
                    self.info.calls_under.setdefault(
                        lock, set()).add(callee)

    def _access(self, attr, line, col):
        if attr in self.m.guard_attrs.get(self.info.sf.rel, ()):
            self.info.accesses.append(
                (attr, "attr", self._held_set(), line, col))

    def _name(self, node):
        if node.id in self.m.guard_globals.get(self.info.sf.rel, ()):
            self.info.accesses.append(
                (node.id, "name", self._held_set(),
                 node.lineno, node.col_offset))


class _Model:
    """The shared concurrency model: built once per lint run, consumed
    by all three passes (cached on the Context)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        conc = getattr(ctx.registry, "CONCURRENCY", None)
        self.locks = tuple(conc.locks) if conc is not None else ()
        self.roles = tuple(conc.roles) if conc is not None else ()
        self.handoffs = tuple(conc.handoffs) if conc is not None else ()
        self.files = [sf for sf in ctx.files if _in_scope(sf)]
        # per-file lookup tables -----------------------------------------
        self.specs_by_module: dict = {}
        for spec in self.locks:
            self.specs_by_module.setdefault(spec.module, []).append(spec)
        self.guard_attrs: dict = {}      # sf.rel -> guarded attr names
        self.guard_globals: dict = {}    # sf.rel -> guarded global names
        self.guards_for: dict = {}       # (sf.rel, name) -> [specs]
        for sf in self.files:
            for spec in self.specs_by_module.get(sf.module, ()):
                for g in spec.guards:
                    self.guards_for.setdefault((sf.rel, g), []).append(
                        spec)
                    if spec.cls:
                        self.guard_attrs.setdefault(sf.rel, set()).add(g)
                    else:
                        self.guard_globals.setdefault(
                            sf.rel, set()).add(g)
                        self.guard_attrs.setdefault(sf.rel, set()).add(g)
        self.module_globals: dict = {}   # sf.rel -> module-level names
        self.fn_globals: dict = {}       # id(fn node) -> `global` names
        self.threading_aliases: dict = {}  # sf.rel -> alias map
        self.raw_locks: list = []
        self.named_ctor_calls: list = []
        self.fns: list = []              # every _FnInfo
        self.fns_by_file: dict = {}      # sf.rel -> [_FnInfo]
        self._collect()
        self._walk()
        self._close()

    # -- collection ----------------------------------------------------
    def _collect(self):
        for sf in self.files:
            top = set()
            for node in sf.tree.body:
                tgts = node.targets if isinstance(node, ast.Assign) else \
                    [node.target] if isinstance(
                        node, (ast.AnnAssign, ast.AugAssign)) else []
                for t in tgts:
                    if isinstance(t, ast.Name):
                        top.add(t.id)
            self.module_globals[sf.rel] = frozenset(top)
            aliases = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name.split(".")[0] == "threading":
                            aliases[(a.asname or a.name).split(".")[0]] \
                                = "threading"
                elif isinstance(node, ast.ImportFrom) and \
                        node.module == "threading":
                    for a in node.names:
                        aliases[a.asname or a.name] = f"t.{a.name}"
                elif isinstance(node,
                                (ast.FunctionDef, ast.AsyncFunctionDef)):
                    decl = set()
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Global):
                            decl.update(sub.names)
                    if decl:
                        self.fn_globals[id(node)] = frozenset(decl)
            self.threading_aliases[sf.rel] = aliases
            # lock constructions anywhere in the file (module level
            # included — _ENGINE_LOCK-style globals are the norm)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = f.id if isinstance(f, ast.Name) else \
                    f.attr if isinstance(f, ast.Attribute) else None
                if name in _RAW_CTORS and self.is_threading_ref(f, sf):
                    self.raw_locks.append(
                        (sf, name, node.lineno, node.col_offset))
                elif name in _NAMED_CTORS:
                    arg = node.args[0] if node.args else None
                    lock_name = arg.value \
                        if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) else None
                    self.named_ctor_calls.append(
                        (sf, lock_name, node.lineno, node.col_offset))
            # functions with their enclosing class
            def visit(body, cls):
                for node in body:
                    if isinstance(node, ast.ClassDef):
                        visit(node.body, node.name)
                    elif isinstance(node, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        info = _FnInfo(sf, node, cls)
                        self.fns.append(info)
                        self.fns_by_file.setdefault(
                            sf.rel, []).append(info)
                        visit(node.body, cls)
            visit(sf.tree.body, "")

    def is_threading_ref(self, func, sf) -> bool:
        aliases = self.threading_aliases.get(sf.rel, {})
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            return aliases.get(func.value.id) == "threading"
        if isinstance(func, ast.Name):
            return aliases.get(func.id, "").startswith("t.")
        return False

    def lock_of(self, expr, info):
        """Resolve a with-item / acquire target to a LockSpec, by
        attribute or bare name within the owning module, disambiguated
        by the enclosing class when a module declares several locks
        under one attribute name."""
        if isinstance(expr, ast.Call):      # with self._lock() style: no
            return None
        name = None
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        if name is None:
            return None
        cands = [s for s in self.specs_by_module.get(info.sf.module, ())
                 if s.attr == name]
        if not cands and info.sf.forced:
            # fixture mode: forced files have no package module; match
            # any registered lock by attribute so scratch fixtures and
            # fake registries exercise the pass
            cands = [s for s in self.locks if s.attr == name]
        if len(cands) == 1:
            return cands[0]
        for s in cands:
            if s.cls == info.cls:
                return s
        return cands[0] if cands else None

    def resolve_guard(self, sf, info, name):
        cands = self.guards_for.get((sf.rel, name), [])
        if not cands and sf.forced:
            cands = [s for s in self.locks if name in s.guards]
        scoped = [s for s in cands if not s.cls or s.cls == info.cls]
        return scoped or cands

    # -- the walks -----------------------------------------------------
    def _walk(self):
        for info in self.fns:
            _FnWalker(self, info).walk()

    def _close(self):
        """Interprocedural closures: ACQ* (locks a call may acquire),
        UNDER (functions assumed to run with a lock held), and the
        final order-edge set."""
        acq: dict = {}                   # fn name -> set of lock names
        edges_by_name: dict = {}         # fn name -> called names
        for info in self.fns:
            acq.setdefault(info.name, set()).update(info.acquires)
            edges_by_name.setdefault(info.name, set()).update(info.calls)
        changed = True
        while changed:
            changed = False
            for name, callees in edges_by_name.items():
                mine = acq.setdefault(name, set())
                before = len(mine)
                for c in callees:
                    mine.update(acq.get(c, ()))
                changed = changed or len(mine) != before
        self.acq_closure = acq
        under: dict = {}                 # lock name -> set of fn names
        for info in self.fns:
            for lock, callees in info.calls_under.items():
                under.setdefault(lock, set()).update(callees)
        for lock, seed in under.items():
            frontier = list(seed)
            while frontier:
                for c in edges_by_name.get(frontier.pop(), ()):
                    if c not in seed:
                        seed.add(c)
                        frontier.append(c)
        self.under = under
        order: set = set()
        order_sites: dict = {}           # (a, b) -> (sf, line)
        for info in self.fns:
            for (a, b) in info.edges:
                order.add((a, b))
                order_sites.setdefault((a, b),
                                       (info.sf, info.node.lineno))
            for lock, callees in info.calls_under.items():
                for c in callees:
                    for inner in self.acq_closure.get(c, ()):
                        order.add((lock, inner))
                        order_sites.setdefault(
                            (lock, inner), (info.sf, info.node.lineno))
        self.order_edges = order
        self.order_sites = order_sites

    def under_lock(self, info, spec) -> bool:
        return info.name in self.under.get(spec.name, ())


def _model(ctx: Context) -> _Model:
    m = getattr(ctx, "_concurrency_model", None)
    if m is None:
        m = ctx._concurrency_model = _Model(ctx)
    return m


def static_lock_edges(root) -> frozenset:
    """The static lock-acquisition graph (name pairs, self-edges
    dropped) over the default lint surface — what the runtime
    LockTracer checks observed acquisition orders against."""
    from .core import load_context
    m = _Model(load_context(root))
    return frozenset((a, b) for a, b in m.order_edges if a != b)


# ---------------------------------------------------------------------------
# pass 7: lock discipline (+ registry liveness)
# ---------------------------------------------------------------------------

def run_lock_discipline(ctx: Context) -> list:
    m = _model(ctx)
    findings: list = []
    registered = {s.name for s in m.locks}
    for sf, kind, line, col in m.raw_locks:
        findings.append(Finding(
            "conc-unregistered-lock", sf.rel, line, col,
            f"bare threading.{kind}() in a concurrency-scoped package — "
            f"invisible to the lock registry and the SPECLINT_TSAN "
            f"tracer",
            hint="construct it via utils.locks.named_lock/named_rlock/"
                 "named_condition with a name declared in "
                 "resilience/sites.py CONCURRENCY"))
    for sf, lock_name, line, col in m.named_ctor_calls:
        if lock_name is None:
            findings.append(Finding(
                "conc-unregistered-lock", sf.rel, line, col,
                "named lock constructor called with a non-literal name "
                "— the registry binding cannot be checked statically",
                hint="pass the canonical name as a string literal"))
        elif lock_name not in registered:
            findings.append(Finding(
                "conc-unregistered-lock", sf.rel, line, col,
                f"lock name {lock_name!r} is not declared in "
                f"resilience/sites.py CONCURRENCY",
                hint="add a LockSpec entry (name, owning module/class, "
                     "attr, kind, guarded attribute set)"))
    for info in m.fns:
        if info.name in ("__init__", "__new__", "__del__"):
            continue        # construction precedes sharing
        for name, kind, held, line, col in info.accesses:
            specs = m.resolve_guard(info.sf, info, name)
            if not specs:
                continue
            ok = any(s.name in held for s in specs) or \
                any(m.under_lock(info, s) for s in specs)
            if ok:
                continue
            locks = " / ".join(s.name for s in specs)
            findings.append(Finding(
                "conc-unguarded-attr", info.sf.rel, line, col,
                f"{name!r} is guarded by {locks} but accessed in "
                f"{info.name}() with no path holding the lock",
                hint="take the lock (or restructure so the access is "
                     "reached only from under it); a deliberately "
                     "lock-free access needs a reasoned disable"))
    findings.extend(_liveness(ctx, m))
    return findings


def _liveness(ctx: Context, m: _Model) -> list:
    """registry-dead-entry: every CONCURRENCY lock/role/handoff and
    every HOST_SYNC_BARRIERS row must resolve to real code.  Full-
    surface runs only — a fixture run sees none of the package files
    and could prove nothing."""
    if not getattr(ctx, "full_surface", False):
        return []
    findings: list = []
    by_module = {sf.module: sf for sf in ctx.files if sf.module}
    sites_sf = next((sf for sf in ctx.files
                     if sf.rel.endswith("resilience/sites.py")), None)

    def where(name: str) -> tuple:
        if sites_sf is not None:
            for i, line in enumerate(sites_sf.lines, 1):
                if f'"{name}"' in line:
                    return sites_sf.rel, i
        return "consensus_specs_tpu/resilience/sites.py", 1

    def dead(name: str, what: str, hint: str) -> None:
        rel, line = where(name)
        findings.append(Finding(
            "registry-dead-entry", rel, line, 0,
            f"{what} — dead registry entry", hint=hint))

    def functions_of(sf):
        out = {}
        def visit(body, cls):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    visit(node.body, node.name)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    out[(cls, node.name)] = node
                    out[("", node.name)] = node
                    visit(node.body, cls)
        visit(sf.tree.body, "")
        return out

    for spec in m.locks:
        sf = by_module.get(spec.module)
        if sf is None:
            dead(spec.name, f"lock {spec.name!r}: module {spec.module} "
                            f"not found", "fix the module path")
            continue
        # one whole-tree walk: module-level bindings and every
        # method-body binding are all under sf.tree
        bound = any(_binds_named_lock(node, spec)
                    for node in ast.walk(sf.tree))
        if not bound:
            dead(spec.name,
                 f"lock {spec.name!r}: no `{spec.attr} = named_*("
                 f"\"{spec.name}\")` binding in {spec.module}",
                 "bind the lock through utils.locks with its registry "
                 "name")
    for role in m.roles:
        if not role.func:
            continue
        sf = by_module.get(role.module)
        fns = functions_of(sf) if sf is not None else {}
        cls, _, fname = role.func.rpartition(".")
        if sf is None or (cls, fname) not in fns:
            dead(role.name, f"role {role.name!r}: entry point "
                            f"{role.module}.{role.func} not found",
                 "fix the role's module/func")
    for h in m.handoffs:
        sf = by_module.get(h.module)
        present = sf is not None and any(
            (isinstance(n, ast.Name) and n.id == h.attr)
            or (isinstance(n, ast.Attribute) and n.attr == h.attr)
            or (isinstance(n, ast.ClassDef) and n.name == h.attr)
            for n in ast.walk(sf.tree))
        if not present:
            dead(h.name, f"handoff {h.name!r}: {h.attr!r} not found in "
                         f"{h.module}", "fix the handoff's module/attr")
    for module, func in getattr(ctx.registry, "HOST_SYNC_BARRIERS", ()):
        sf = by_module.get(module)
        fns = functions_of(sf) if sf is not None else {}
        if sf is None or ("", func) not in fns:
            dead(func, f"HOST_SYNC_BARRIERS: {module}.{func} not found",
                 "fix the barrier's module/function")
    return findings


def _binds_named_lock(node, spec) -> bool:
    """`<attr> = named_*("<name>")` (plain or chained assignment)."""
    if not isinstance(node, ast.Assign) or \
            not isinstance(node.value, ast.Call):
        return False
    f = node.value.func
    fname = f.id if isinstance(f, ast.Name) else \
        f.attr if isinstance(f, ast.Attribute) else None
    if fname not in _NAMED_CTORS:
        return False
    args = node.value.args
    if not (args and isinstance(args[0], ast.Constant)
            and args[0].value == spec.name):
        return False
    for t in node.targets:
        if isinstance(t, ast.Name) and t.id == spec.attr:
            return True
        if isinstance(t, ast.Attribute) and t.attr == spec.attr:
            return True
        if isinstance(t, ast.Subscript):
            return True     # dict-slot binding (per-site worker locks)
    return False


# ---------------------------------------------------------------------------
# pass 8: lock order
# ---------------------------------------------------------------------------

def run_lock_order(ctx: Context) -> list:
    m = _model(ctx)
    findings: list = []
    kind_of = {s.name: s.kind for s in m.locks}
    graph: dict = {}
    for a, b in m.order_edges:
        if a == b:
            if kind_of.get(a) == "lock":
                sf, line = m.order_sites[(a, b)]
                findings.append(Finding(
                    "conc-lock-order-cycle", sf.rel, line, 0,
                    f"non-reentrant lock {a!r} re-acquired while held — "
                    f"guaranteed self-deadlock",
                    hint="make it an rlock or hoist the inner acquire"))
            continue
        graph.setdefault(a, set()).add(b)
    # cycle detection: iterative DFS with colors
    color: dict = {}
    stack_path: list = []
    cycles: list = []

    def dfs(node):
        color[node] = 1
        stack_path.append(node)
        for nxt in sorted(graph.get(node, ())):
            if color.get(nxt, 0) == 0:
                dfs(nxt)
            elif color.get(nxt) == 1:
                cycles.append(tuple(stack_path[stack_path.index(nxt):])
                              + (nxt,))
        stack_path.pop()
        color[node] = 2

    for node in sorted(graph):
        if color.get(node, 0) == 0:
            dfs(node)
    seen = set()
    for cyc in cycles:
        key = frozenset(cyc)
        if key in seen:
            continue
        seen.add(key)
        edge = (cyc[0], cyc[1])
        sf, line = m.order_sites.get(edge, (None, 1))
        rel = sf.rel if sf is not None else \
            "consensus_specs_tpu/resilience/sites.py"
        findings.append(Finding(
            "conc-lock-order-cycle", rel, line, 0,
            f"static lock-acquisition cycle: {' -> '.join(cyc)} — "
            f"two threads taking these in opposite order deadlock",
            hint="impose one global order (registry note) and "
                 "restructure the acquisition that breaks it"))
    return findings


# ---------------------------------------------------------------------------
# pass 9: thread escape
# ---------------------------------------------------------------------------

def run_thread_escape(ctx: Context) -> list:
    m = _model(ctx)
    findings: list = []
    handoff_attrs: dict = {}
    for h in m.handoffs:
        handoff_attrs.setdefault(h.module, set()).add(h.attr)
    for role in m.roles:
        if not role.func:
            continue                    # the implicit block-thread role
        _, _, entry = role.func.rpartition(".")
        infos = [i for i in m.fns_by_file.get(_rel_of(m, role.module),
                                              [])]
        if not infos and any(sf.forced for sf in m.files):
            infos = [i for sf in m.files if sf.forced
                     for i in m.fns_by_file.get(sf.rel, [])]
        by_name: dict = {}
        for i in infos:
            by_name.setdefault(i.name, []).append(i)
        if entry not in by_name:
            continue                    # liveness pass reports it
        reach = {entry}
        frontier = [entry]
        while frontier:
            for i in by_name.get(frontier.pop(), []):
                for c in i.calls:
                    if c in by_name and c not in reach:
                        reach.add(c)
                        frontier.append(c)
        module = infos[0].sf.module if infos else role.module
        allowed = handoff_attrs.get(role.module, set()) | \
            handoff_attrs.get(module, set())
        for name in reach:
            for info in by_name[name]:
                if info.name in ("__init__", "__new__"):
                    continue
                for root, tgt, held, line, col in _escapes(m, info):
                    if held:
                        continue        # lock-guarded: discipline pass
                        #                 owns whether it's the RIGHT one
                    if tgt in allowed:
                        continue
                    if any(m.under_lock(info, s) for s in m.locks):
                        continue
                    findings.append(Finding(
                        "conc-thread-escape", info.sf.rel, line, col,
                        f"{info.name}() runs on the {role.name!r} "
                        f"worker role and mutates shared "
                        f"{'attribute' if root == 'self' else 'global'} "
                        f"{tgt!r} with no lock held and no registered "
                        f"handoff",
                        hint="guard it with a registered lock, route "
                             "it through a CONCURRENCY handoff, or a "
                             "nodectx Router; thread-local state is "
                             "exempt by registration"))
    # dedup: two roles sharing one entry point (engine + leg workers)
    # would otherwise double-report the same line
    out, seen = [], set()
    for f in findings:
        key = (f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def _rel_of(m, module: str) -> str:
    for sf in m.files:
        if sf.module == module:
            return sf.rel
    return ""


def _escapes(m, info):
    """Mutations recorded for `info`: direct assignments plus mutator-
    method calls rooted at self attributes or module globals."""
    yield from info.mutations
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            root, attr = _root_name(node.func.value)
            held = _held_at(m, info, node.lineno)
            if root == "self" and attr:
                yield ("self", attr, held, node.lineno, node.col_offset)
            elif root == "self" and not attr:
                continue
            elif root in m.module_globals.get(info.sf.rel, ()):
                yield ("global", root, held, node.lineno,
                       node.col_offset)


def _held_at(m, info, line: int) -> frozenset:
    """Approximate the held-lock set at `line` from the recorded
    guarded-access walk: re-walk is avoided by checking whether any
    with-region of the function covers the line."""
    held = set()
    for node in ast.walk(info.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                for item in node.items:
                    spec = m.lock_of(item.context_expr, info)
                    if spec is not None:
                        held.add(spec.name)
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire" and node.lineno <= line:
            spec = m.lock_of(node.func.value, info)
            if spec is not None:
                held.add(spec.name)
    return frozenset(held)


def run(ctx: Context) -> list:
    """All three concurrency passes (the driver calls the named
    runners individually; this is the convenience aggregate)."""
    return (run_lock_discipline(ctx) + run_lock_order(ctx)
            + run_thread_escape(ctx))
