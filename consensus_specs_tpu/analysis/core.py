"""speclint core: findings, the disable escape hatch, source loading,
and the pass driver.

Everything here is stdlib-``ast`` only — linting never imports jax, the
crypto packages, or anything else heavy; the one package module it
loads (resilience/sites.py, the canonical seam registry) is loaded
standalone by file path, bypassing the package ``__init__`` chain, so a
full-repo run stays well under the 10 s CI budget.

The escape hatch: a violating line may carry

    # speclint: disable=<rule>[,<rule>...] -- <reason>

(or the comment may stand alone on the line directly above).  The
reason is mandatory — a disable without one is itself a finding
(``speclint-bad-disable``), as is a disable naming an unknown rule.
The policy is docs/analysis.md: the comment documents WHY the invariant
does not apply, it never waives the obligation to have an answer.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

# every rule any pass may emit; disables are validated against this
RULES: dict[str, str] = {
    "seam-unregistered-site":
        "a dispatch/fire/FaultSpec site name is not in resilience/sites.py",
    "seam-dynamic-site":
        "a seam call's site argument cannot be resolved statically",
    "seam-missing-fallback":
        "a dispatch call does not pass a fallback_fn",
    "site-undocumented":
        "a registered site is missing from the docs site table",
    "site-unused":
        "a registered site has no dispatch/fire call site in the code",
    "bypass-direct-kernel":
        "a device-kernel module is imported outside a registered wrapper",
    "det-wall-clock":
        "a decision path reads the wall clock instead of an injected clock",
    "det-unseeded-rng":
        "a decision path draws from an unseeded entropy source",
    "global-mutable-state":
        "a module-level mutable container is neither a nodectx Router "
        "nor registered",
    "txn-unwrapped-store-write":
        "a Store field write is reachable from no @transactional handler",
    "async-host-sync":
        "a host-sync primitive (device_get/block_until_ready/np.asarray) "
        "sits outside a declared join barrier in a pipelined package",
    "conc-unregistered-lock":
        "a bare threading lock (or a named lock with an unregistered "
        "name) in a concurrency-scoped package",
    "conc-unguarded-attr":
        "an attribute a registered lock guards is accessed with no path "
        "holding the lock",
    "conc-lock-order-cycle":
        "the static lock-acquisition graph has a cycle (or a "
        "non-reentrant lock self-edge): potential deadlock",
    "conc-thread-escape":
        "a worker-role function mutates shared state that is neither "
        "lock-guarded nor a registered cross-thread handoff",
    "registry-dead-entry":
        "a CONCURRENCY or HOST_SYNC_BARRIERS registry entry resolves to "
        "no code",
    "fold-unaware-pairing":
        "a pairing_product call bypasses the fold-aware entry "
        "(sigpipe.scheduler / the ops.pairing_fold seam)",
    "factory-scalar-bypass":
        "factory code imports crypto.* or calls a scalar BLS/KZG oracle "
        "verb instead of riding the registered engine seams",
    "node-scalar-bypass":
        "node code imports crypto.* or calls a scalar BLS/KZG oracle "
        "verb instead of feeding the admission pipeline's counted seams",
    "epoch-scalar-bypass":
        "package code imports the ops.epoch_sweep device program or "
        "reaches epoch_fast internals instead of riding the registered "
        "ops.epoch_sweep seam (or the scalar_epoch escape hatch)",
    "speclint-bad-disable":
        "a speclint disable comment lacks a reason or names an unknown rule",
}

_DISABLE_RE = re.compile(
    r"#\s*speclint:\s*disable=([A-Za-z0-9_,\s-]+?)\s*(?:--\s*(.*?)\s*)?$")


@dataclass(frozen=True)
class Finding:
    """One violation: file:line plus rule id and a fix hint."""

    rule: str
    path: str       # repo-relative, slash-separated
    line: int
    col: int
    message: str
    hint: str = ""

    def render(self) -> str:
        tail = f"  [{self.hint}]" if self.hint else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}: {self.message}{tail}")

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message, "hint": self.hint}


@dataclass
class Disable:
    rules: tuple[str, ...]
    reason: str
    line: int           # the commented line itself
    applies_to: int     # the line findings must match to be suppressed


class SourceFile:
    """One parsed source file plus everything the passes ask of it."""

    def __init__(self, path: Path, rel: str, text: str,
                 forced: bool = False):
        self.path = path
        self.rel = rel                      # repo-relative, posix
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        self.forced = forced                # explicit target: all passes apply
        # dotted module name for package files ("" outside the package)
        parts = Path(rel).with_suffix("").parts
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        self.module = ".".join(parts) if parts and \
            parts[0] == "consensus_specs_tpu" else ""
        self.is_package = rel.endswith("__init__.py")
        self.disables: list[Disable] = self._scan_disables()

    def _scan_disables(self) -> list[Disable]:
        # real COMMENT tokens only: disable-looking text inside
        # docstrings or string literals (usage examples, hints) must
        # neither suppress findings nor trip speclint-bad-disable
        out = []
        if "speclint:" not in self.text:
            return out          # skip tokenizing the common case
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except tokenize.TokenError:
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE_RE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            reason = (m.group(2) or "").strip()
            if self.lines[i - 1].strip().startswith("#"):
                # a standalone comment guards the next CODE line (the
                # reason may wrap over several comment lines)
                applies = i + 1
                while applies <= len(self.lines) and (
                        not self.lines[applies - 1].strip()
                        or self.lines[applies - 1].strip().startswith("#")):
                    applies += 1
            else:
                applies = i
            out.append(Disable(rules, reason, i, applies))
        return out

    def suppressed(self, rule: str, line: int) -> bool:
        return any(rule in d.rules and d.applies_to == line and d.reason
                   for d in self.disables)

    def in_module(self, *prefixes: str) -> bool:
        """Pass scoping: explicit targets are always in scope."""
        if self.forced:
            return True
        return any(self.module == p or self.module.startswith(p + ".")
                   for p in prefixes)


def disable_findings(sf: SourceFile) -> list[Finding]:
    """Malformed escape hatches are violations in their own right."""
    out = []
    for d in sf.disables:
        if not d.reason:
            out.append(Finding(
                "speclint-bad-disable", sf.rel, d.line, 0,
                "disable comment must cite a reason: "
                "`# speclint: disable=<rule> -- <why the invariant "
                "does not apply here>`"))
        for r in d.rules:
            if r not in RULES:
                out.append(Finding(
                    "speclint-bad-disable", sf.rel, d.line, 0,
                    f"disable names unknown rule {r!r}",
                    hint="known rules are listed in docs/analysis.md"))
    return out


# directories never worth parsing
_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "build", "out",
              "node_modules"}

# default lint surface: the whole package, plus the one test module
# whose site tuples are contractual (other tests use synthetic site
# names on purpose — they exercise the seam machinery itself)
_DEFAULT_TARGETS = ("consensus_specs_tpu", "tests/test_chaos.py")


def _iter_py(root: Path):
    for target in _DEFAULT_TARGETS:
        p = root / target
        if p.is_file():
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not _SKIP_DIRS.intersection(f.relative_to(root).parts):
                    yield f


class Context:
    """Shared state for one lint run: sources + the loaded registry."""

    def __init__(self, root: Path, files: list[SourceFile], registry):
        self.root = root
        self.files = files
        self.registry = registry


# full-surface parse cache: one (path, mtime, size) -> SourceFile map.
# The quick tier runs several whole-tree lints (repo-is-clean gates for
# three seam passes + registry liveness); parsing the package dominates
# each, and SourceFiles are read-only after construction, so re-lints
# only re-parse files that actually changed.
_PARSE_CACHE: dict = {}


def _cached_source(p: Path, rel: str) -> SourceFile:
    stat = p.stat()
    key = str(p)
    hit = _PARSE_CACHE.get(key)
    if hit is not None and hit[0] == (stat.st_mtime_ns, stat.st_size):
        return hit[1]
    sf = SourceFile(p, rel, p.read_text())
    _PARSE_CACHE[key] = ((stat.st_mtime_ns, stat.st_size), sf)
    return sf


def load_context(root: str | Path,
                 paths: list[str | Path] | None = None) -> Context:
    """Parse the lint surface.  With `paths`, lint exactly those files
    (marked `forced`: every pass applies regardless of module scoping —
    the fixture/scratch mode); otherwise the package + tests/test_chaos.py.
    """
    from .registry import load_registry
    root = Path(root).resolve()
    files = []
    if paths is None:
        for p in _iter_py(root):
            rel = p.relative_to(root).as_posix()
            files.append(_cached_source(p, rel))
    else:
        for p in map(Path, paths):
            p = p.resolve()
            try:
                rel = p.relative_to(root).as_posix()
            except ValueError:
                rel = p.name
            files.append(SourceFile(p, rel, p.read_text(), forced=True))
    ctx = Context(root, files, load_registry(root))
    # registry-liveness checks only make sense when the whole package
    # surface is loaded — a fixture run sees none of it
    ctx.full_surface = paths is None
    return ctx


def _pass_table() -> dict:
    """Ordered name -> runner table (the CLI's --pass / --list-passes
    vocabulary).  Import is deferred so `from .core import Finding`
    inside the pass modules does not cycle."""
    from . import (bypass, concurrency, determinism, epochseam,
                   factoryseam, foldgate, globals_, hostsync, nodeseam,
                   seams, txnpurity)
    return {
        "seams": seams.run,
        "bypass": bypass.run,
        "determinism": determinism.run,
        "globals": globals_.run,
        "txnpurity": txnpurity.run,
        "hostsync": hostsync.run,
        "lock-discipline": concurrency.run_lock_discipline,
        "lock-order": concurrency.run_lock_order,
        "thread-escape": concurrency.run_thread_escape,
        "foldgate": foldgate.run,
        "factoryseam": factoryseam.run,
        "nodeseam": nodeseam.run,
        "epochseam": epochseam.run,
    }


def pass_names() -> tuple:
    return tuple(_pass_table())


def run_speclint(root: str | Path,
                 paths: list[str | Path] | None = None,
                 passes: list[str] | None = None) -> list[Finding]:
    """Run every pass (or just `passes`, by name — see
    :func:`pass_names`); returns surviving findings sorted by location.

    Disable comments suppress same-line (or next-line, for standalone
    comments) findings of the named rules — but only when they cite a
    reason; malformed disables surface as `speclint-bad-disable`.
    """
    table = _pass_table()
    if passes is not None:
        unknown = [p for p in passes if p not in table]
        if unknown:
            raise RuntimeError(
                f"unknown pass(es): {', '.join(unknown)} "
                f"(known: {', '.join(table)})")
        table = {name: table[name] for name in table if name in passes}
    ctx = load_context(root, paths)
    findings: list[Finding] = []
    for runner in table.values():
        findings.extend(runner(ctx))
    by_rel = {sf.rel: sf for sf in ctx.files}
    kept = []
    for f in findings:
        sf = by_rel.get(f.path)
        if sf is not None and sf.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    for sf in ctx.files:
        kept.extend(disable_findings(sf))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept
