"""Pass 5 — transactional purity.

Since PR 4 every fork-choice store mutation must be atomic-or-absent:
handlers are decorated ``@transactional`` and their writes land in a
copy-on-write overlay.  The invariant a new handler can silently break
is forgetting the decorator — its writes would hit the base store
directly, invisible to the journal, the kill points, and recovery.

Statically: any function that writes through a parameter named
``store`` must either be decorated ``@transactional`` or be reachable
(by name, over the package-wide self/direct call graph) from a
decorated handler — helpers like ``update_checkpoints`` run inside the
caller's transaction.  The txn machinery itself and the offline
harnesses (test_infra, spec_tests, gen, debug) are out of scope: they
ARE the implementation / drive stores outside node runtime.
"""
from __future__ import annotations

import ast

from .core import Context, Finding

_EXEMPT = (
    "consensus_specs_tpu.txn",          # the commit/overlay machinery
    "consensus_specs_tpu.test_infra",   # test-side store drivers
    "consensus_specs_tpu.spec_tests",   # in-package test suites
    "consensus_specs_tpu.gen",          # offline vector generation
    "consensus_specs_tpu.debug",
    # the light-client `store` parameter is a LightClientStore — a sync-
    # protocol object the txn overlay never wraps; the PR 4 contract
    # covers the fork-choice Store only
    "consensus_specs_tpu.specs.light_client",
)

_MUTATORS = frozenset({
    "append", "add", "update", "pop", "clear", "extend", "insert",
    "setdefault", "remove", "discard", "popitem",
})


def _roots_at_store(expr: ast.expr) -> bool:
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return isinstance(expr, ast.Name) and expr.id == "store"


def _writes_store(fn: ast.AST) -> int | None:
    """First line where `fn` writes through its `store` parameter."""
    for node in ast.walk(fn):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                _roots_at_store(node.func.value):
            # store.blocks.update(...) style in-place mutation; reads
            # like store.blocks[r] stay untouched
            return node.lineno
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) and \
                    _roots_at_store(t):
                return t.lineno
    return None


def _has_store_param(fn) -> bool:
    a = fn.args
    return any(p.arg == "store"
               for p in (a.posonlyargs + a.args + a.kwonlyargs))


def _is_transactional(fn) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = d.attr if isinstance(d, ast.Attribute) else \
            d.id if isinstance(d, ast.Name) else None
        if name == "transactional":
            return True
    return False


def _called_names(fn) -> set[str]:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls", "spec"):
                out.add(f.attr)
    return out


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _exempt(sf) -> bool:
    # deliberately ignores `forced`: an exempt module stays exempt even
    # when linted explicitly, but a scratch fixture (no module) never is
    return any(sf.module == p or sf.module.startswith(p + ".")
               for p in _EXEMPT)


def run(ctx: Context) -> list[Finding]:
    in_scope = [sf for sf in ctx.files
                if (sf.module or sf.forced) and not _exempt(sf)]
    # package-wide name call graph + transactional roots
    edges: dict[str, set[str]] = {}
    roots: set[str] = set()
    writers = []        # (sf, fn, first write line)
    for sf in in_scope:
        for fn in _functions(sf.tree):
            edges.setdefault(fn.name, set()).update(_called_names(fn))
            if _is_transactional(fn):
                roots.add(fn.name)
            if _has_store_param(fn):
                line = _writes_store(fn)
                if line is not None:
                    writers.append((sf, fn, line))
    reach = set(roots)
    frontier = list(roots)
    while frontier:
        for callee in edges.get(frontier.pop(), ()):
            if callee not in reach:
                reach.add(callee)
                frontier.append(callee)
    findings = []
    for sf, fn, line in writers:
        if _is_transactional(fn) or fn.name in reach:
            continue
        findings.append(Finding(
            "txn-unwrapped-store-write", sf.rel, line, 0,
            f"{fn.name}() writes the fork-choice store but is neither "
            f"@transactional nor reachable from a transactional handler",
            hint="decorate the handler with @txn.transactional (or call "
                 "it only from one) so the write is atomic-or-absent"))
    return findings
