"""Standalone access to the canonical seam registry and the docs table.

speclint must not import the package it lints (a lint run should never
pay a jax import, and a broken package must still lint), so the
registry module — resilience/sites.py, which itself imports only
stdlib — is loaded by file path with importlib, bypassing
``consensus_specs_tpu/__init__`` and the resilience package
``__init__`` entirely.
"""
from __future__ import annotations

import importlib.util
import re
import sys
from pathlib import Path

_SITES_REL = Path("consensus_specs_tpu") / "resilience" / "sites.py"


def load_registry(root: Path):
    """The live resilience/sites.py module, loaded standalone."""
    path = Path(root) / _SITES_REL
    spec = importlib.util.spec_from_file_location(
        "_speclint_sites", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves cls.__module__ through sys.modules at class
    # creation; register before exec so the standalone load works
    sys.modules["_speclint_sites"] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop("_speclint_sites", None)
    return mod


_BACKTICK_SITE_RE = re.compile(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`")


def documented_sites(root: Path, doc_rel: str) -> frozenset[str]:
    """Every backticked dotted-lowercase token in `doc_rel`'s markdown
    TABLE rows — prose mentions don't count, so the forward check
    (registry ⊆ doc) enforces exactly what docs/resilience.md promises:
    registering a seam obliges a site-table row."""
    path = Path(root) / doc_rel
    if not path.is_file():
        return frozenset()
    rows = [line for line in path.read_text().splitlines()
            if line.lstrip().startswith("|")]
    return frozenset(_BACKTICK_SITE_RE.findall("\n".join(rows)))
