"""SSZ view -> jsonable (yaml-dumpable) structure.

Same on-disk conventions as the reference's debug/encode.py so generated
vectors stay interchangeable: uints wider than 64 bits and uint64 values
become decimal strings (yaml can't hold full uint64 precision), byte
strings become 0x-hex, bit types dump their serialized byte form.
"""
from __future__ import annotations

from ..ssz.types import (
    uint, boolean, Bitvector, Bitlist, ByteVector, ByteList,
    Vector, List, Container, Union,
)


def encode(value):
    if isinstance(value, boolean):
        return bool(value)
    if isinstance(value, uint):
        if value.type_byte_length() > 8 or int(value) >= 2 ** 63:
            return str(int(value))
        return int(value)
    if isinstance(value, (ByteVector, ByteList)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (Bitvector, Bitlist)):
        return "0x" + value.serialize().hex()
    if isinstance(value, (Vector, List)):
        return [encode(elem) for elem in value]
    if isinstance(value, Union):
        return {"selector": int(value.selector),
                "value": None if value.value is None else encode(value.value)}
    if isinstance(value, Container):
        return {name: encode(getattr(value, name))
                for name in value.fields()}
    raise TypeError(f"cannot encode {type(value)!r}")
