"""Random SSZ object generation for fuzz/static tests.

Same capability as the reference's debug/random_value.py (six
RandomizationModes driving value and length choices), rebuilt over our own
type descriptors (ssz/types.py).  Used by the ssz_static-style tests and
the test-vector generators.
"""
from __future__ import annotations

from enum import Enum
from random import Random

from ..ssz.types import (
    uint, boolean, Bitvector, Bitlist, ByteVector, ByteList,
    Vector, List, Container, Union,
)


class RandomizationMode(Enum):
    RANDOM = 0          # uniformly random values, random lengths
    ZERO = 1            # minimal/zero values
    MAX = 2             # maximal values
    NIL_COUNT = 3       # random values, zero-length collections
    ONE_COUNT = 4       # random values, single-element collections
    MAX_COUNT = 5       # random values, limit-length collections


def _random_length(mode: RandomizationMode, rng: Random,
                   max_len: int, limit: int) -> int:
    cap = min(max_len, limit)
    if mode == RandomizationMode.ZERO:
        return 0
    if mode == RandomizationMode.NIL_COUNT:
        return 0
    if mode == RandomizationMode.ONE_COUNT:
        return min(1, cap)
    if mode in (RandomizationMode.MAX, RandomizationMode.MAX_COUNT):
        return cap
    return rng.randint(0, cap)


def get_random_ssz_object(rng: Random, typ, max_bytes_length: int = 256,
                          max_list_length: int = 8,
                          mode: RandomizationMode = RandomizationMode.RANDOM,
                          chaos: bool = False):
    """Build a random instance of `typ`.

    `chaos` re-rolls the mode per element/field so one object mixes
    zero/max/random regions (the reference's chaos flag).
    """
    if chaos:
        mode = rng.choice(list(RandomizationMode))

    if issubclass(typ, boolean):
        if mode == RandomizationMode.ZERO:
            return typ(False)
        if mode == RandomizationMode.MAX:
            return typ(True)
        return typ(rng.choice((True, False)))

    if issubclass(typ, uint):
        bits = 8 * typ.type_byte_length()
        if mode == RandomizationMode.ZERO:
            return typ(0)
        if mode == RandomizationMode.MAX:
            return typ((1 << bits) - 1)
        return typ(rng.getrandbits(bits))

    if issubclass(typ, ByteVector):
        n = typ.LENGTH
        if mode == RandomizationMode.ZERO:
            return typ(b"\x00" * n)
        if mode == RandomizationMode.MAX:
            return typ(b"\xff" * n)
        return typ(bytes(rng.getrandbits(8) for _ in range(n)))

    if issubclass(typ, ByteList):
        n = _random_length(mode, rng, max_bytes_length, typ.LIMIT)
        fill = (b"\x00" if mode == RandomizationMode.ZERO
                else b"\xff" if mode == RandomizationMode.MAX else None)
        if fill is not None:
            return typ(fill * n)
        return typ(bytes(rng.getrandbits(8) for _ in range(n)))

    if issubclass(typ, Bitvector):
        if mode == RandomizationMode.ZERO:
            return typ([False] * typ.LENGTH)
        if mode == RandomizationMode.MAX:
            return typ([True] * typ.LENGTH)
        return typ([rng.choice((True, False)) for _ in range(typ.LENGTH)])

    if issubclass(typ, Bitlist):
        n = _random_length(mode, rng, max_list_length, typ.LIMIT)
        if mode == RandomizationMode.ZERO:
            return typ([False] * n)
        if mode == RandomizationMode.MAX:
            return typ([True] * n)
        return typ([rng.choice((True, False)) for _ in range(n)])

    if issubclass(typ, Vector):
        return typ([
            get_random_ssz_object(rng, typ.ELEM_TYPE, max_bytes_length,
                                  max_list_length, mode, chaos)
            for _ in range(typ.LENGTH)])

    if issubclass(typ, List):
        n = _random_length(mode, rng, max_list_length, typ.LIMIT)
        return typ([
            get_random_ssz_object(rng, typ.ELEM_TYPE, max_bytes_length,
                                  max_list_length, mode, chaos)
            for _ in range(n)])

    if issubclass(typ, Union):
        options = typ.OPTIONS
        if mode == RandomizationMode.ZERO:
            sel = 0
        elif mode == RandomizationMode.MAX:
            sel = len(options) - 1
        else:
            sel = rng.randrange(len(options))
        opt = options[sel]
        if opt is None:
            return typ(sel, None)
        return typ(sel, get_random_ssz_object(
            rng, opt, max_bytes_length, max_list_length, mode, chaos))

    if issubclass(typ, Container):
        return typ(**{
            name: get_random_ssz_object(rng, ftyp, max_bytes_length,
                                        max_list_length, mode, chaos)
            for name, ftyp in typ.fields().items()})

    raise TypeError(f"cannot generate a random {typ!r}")
