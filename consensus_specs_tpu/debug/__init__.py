"""Debug/inspection tools: random SSZ objects and SSZ<->jsonable codecs.

Capability counterpart of the reference's
/root/reference/tests/core/pyspec/eth2spec/debug/{random_value,encode,decode}.py.
"""
from .random_value import RandomizationMode, get_random_ssz_object  # noqa: F401
from .encode import encode  # noqa: F401
from .decode import decode  # noqa: F401
