"""Jsonable structure -> SSZ view (inverse of encode.py)."""
from __future__ import annotations

from ..ssz.types import (
    uint, boolean, Bitvector, Bitlist, ByteVector, ByteList,
    Vector, List, Container, Union,
)


def decode(data, typ):
    if issubclass(typ, boolean):
        return typ(data)
    if issubclass(typ, uint):
        return typ(int(data))
    if issubclass(typ, (ByteVector, ByteList)):
        if isinstance(data, str):
            return typ(bytes.fromhex(data[2:] if data.startswith("0x")
                                     else data))
        return typ(bytes(data))
    if issubclass(typ, (Bitvector, Bitlist)):
        if isinstance(data, str):
            raw = bytes.fromhex(data[2:] if data.startswith("0x") else data)
        else:
            raw = bytes(data)
        return typ.deserialize(raw)
    if issubclass(typ, (Vector, List)):
        return typ([decode(elem, typ.ELEM_TYPE) for elem in data])
    if issubclass(typ, Union):
        sel = int(data["selector"])
        opt = typ.OPTIONS[sel]
        if opt is None:
            return typ(sel, None)
        return typ(sel, decode(data["value"], opt))
    if issubclass(typ, Container):
        return typ(**{name: decode(data[name], ftyp)
                      for name, ftyp in typ.fields().items()})
    raise TypeError(f"cannot decode into {typ!r}")
