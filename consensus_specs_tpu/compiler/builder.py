"""Spec builder: merge parsed fork docs and emit an executable module.

Capability counterpart of the reference's pysetup pipeline
(/root/reference/pysetup/helpers.py:37-273 `objects_to_spec`,
`combine_spec_objects`, `dependency_order_class_objects` and
setup.py:373 `build_spec`):

- fork docs merge in order, newer definitions override older ones
- SSZ container classes are emitted in field-dependency fixpoint order
- preset vars bake in as module constants (shape-defining, compile-time)
- config vars land in a mutable `config` namespace (runtime-swappable,
  the reference's two-tier preset/config split)
- the emitted source execs against our runtime (ssz types, bls shim,
  hash) into a real module object
"""
from __future__ import annotations

import ast
import re
import textwrap
import types

from .parser import ParsedSpec, _eval_literal, parse_markdown, parse_value

_HEADER = '''\
"""GENERATED spec module — consensus_specs_tpu.compiler output."""
from dataclasses import dataclass, field
from typing import (
    Any, Callable, Dict, NamedTuple, Optional, Protocol, Sequence, Set,
    Tuple, TypeVar)

T = TypeVar("T")
TPoint = TypeVar("TPoint")
from consensus_specs_tpu.ssz import (
    boolean, uint, uint8, uint16, uint32, uint64, uint128, uint256,
    Bitlist, Bitvector, ByteList, ByteVector, List, Vector, Container,
    Union, Bytes1, Bytes4, Bytes8, Bytes20, Bytes31, Bytes32, Bytes48,
    Bytes96, hash_tree_root, serialize, uint_to_bytes,
)
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.hash import hash


def copy(value):
    return value.copy()


# annotation-only aliases the reference injects via its builders
SSZObject = Container
SSZVariableName = str
GeneralizedIndex = int
'''


class Config(types.SimpleNamespace):
    """Runtime-swappable config namespace."""


_SAFE_EXPR_NODES = (
    ast.Expression, ast.Constant, ast.Name, ast.Load, ast.Call,
    ast.BinOp, ast.UnaryOp, ast.Tuple, ast.List, ast.keyword,
    ast.Add, ast.Sub, ast.Mult, ast.Pow, ast.FloorDiv, ast.Mod,
    ast.USub, ast.LShift, ast.RShift, ast.BitOr, ast.BitAnd,
)


_MAX_CONST_BITS = 1 << 16

# The only names a constant cell may CALL: the runtime casts/type
# constructors the generated module's header imports.  Cells calling
# anything else — ``eval``, ``pow``, ``__import__`` chains — are PUBLIC
# markdown trying to execute code at module-exec time and fail the gate.
# Spec-defined custom types (Slot, Epoch, Gwei, …) extend this set per
# build via the ``extra_callees`` argument.
_RUNTIME_CALLEES = frozenset({
    "boolean", "uint", "uint8", "uint16", "uint32", "uint64", "uint128",
    "uint256", "Bytes1", "Bytes4", "Bytes8", "Bytes20", "Bytes31",
    "Bytes32", "Bytes48", "Bytes96", "ByteList", "ByteVector",
})

# result-magnitude bound by callee semantics: a cast cannot produce a
# value wider than the target type, whatever its argument was
_CALLEE_BITS = {
    "boolean": 1, "uint8": 8, "uint16": 16, "uint32": 32, "uint64": 64,
    "uint128": 128, "uint256": 256,
    "Bytes1": 8, "Bytes4": 32, "Bytes8": 64, "Bytes20": 160,
    "Bytes31": 248, "Bytes32": 256, "Bytes48": 384, "Bytes96": 768,
}


def _may_be_sequence(node, seq_names: frozenset) -> bool:
    """Could this subtree evaluate to a str/bytes/tuple/list?
    `seq_names` is the build's type knowledge: a Name bound to a
    byte/tuple-valued constant (GENESIS_FORK_VERSION, a tuple literal)
    or a call through a byte-typed custom type (Root('0x…')) is a
    sequence — repeating one multiplies its size, so the integer Mult
    bound must not apply to it."""
    if isinstance(node, ast.Constant):
        return not isinstance(node.value, (int, bool))
    if isinstance(node, (ast.Tuple, ast.List)):
        return True
    if isinstance(node, ast.Name):
        return node.id in seq_names
    if isinstance(node, ast.Call):
        callee = node.func.id if isinstance(node.func, ast.Name) else ""
        return callee.startswith(("Bytes", "ByteVector", "ByteList")) \
            or callee in seq_names
    if isinstance(node, ast.BinOp):
        return _may_be_sequence(node.left, seq_names) \
            or _may_be_sequence(node.right, seq_names)
    if isinstance(node, ast.UnaryOp):
        return _may_be_sequence(node.operand, seq_names)
    return False


def _bit_bound(node, seq_names: frozenset = frozenset()) -> int:
    """Abstract upper bound on the bit-length a cell expression can
    produce when the generated module exec's it.  Names are assumed to
    be ≤256-bit spec constants; exponents/shifts must be small static
    literals.  Composes through the whole tree, so nested forms like
    ``((2**4096)**4096)**4096`` are bounded (each Pow multiplies the
    operand's bound), closing the build-hang DoS a per-node exponent
    check misses."""
    if isinstance(node, ast.Expression):
        return _bit_bound(node.body, seq_names)
    if isinstance(node, ast.Constant):
        if isinstance(node.value, int):
            return max(int(node.value).bit_length(), 1)
        return max(len(str(node.value)) * 8, 1)
    if isinstance(node, ast.Name):
        # byte-typed names can be wider than any uint (Bytes96 = 768
        # bits; string-literal constants unbounded in principle) — use a
        # bound that still trips the cap after modest repetition
        return 1024 if node.id in seq_names else 256
    if isinstance(node, ast.Call):
        # Python evaluates every argument (positional AND keyword)
        # before the callee runs, so the evaluation COST must stay
        # under the cap regardless of the callee's result width — a
        # cast truncates its result, it does not shrink the 17 GB
        # integer the interpreter built to pass in
        arg_bits = [_bit_bound(a, seq_names) for a in node.args]
        arg_bits += [_bit_bound(kw.value, seq_names)
                     for kw in node.keywords]
        if max(arg_bits, default=0) > _MAX_CONST_BITS:
            raise ValueError("call argument magnitude exceeds cap")
        callee = node.func.id if isinstance(node.func, ast.Name) else ""
        if callee in _CALLEE_BITS:
            return _CALLEE_BITS[callee]
        if callee in seq_names:
            return 1024  # byte-typed custom type of statically unknown width
        return max(arg_bits + [256])
    if isinstance(node, ast.Subscript):
        # type expressions: List[X, N * M] — bound the index cost
        return max(_bit_bound(node.value, seq_names),
                   _bit_bound(node.slice, seq_names))
    if isinstance(node, (ast.Tuple, ast.List)):
        return max([_bit_bound(e, seq_names)
                    for e in node.elts] + [1])
    if isinstance(node, ast.UnaryOp):
        return _bit_bound(node.operand, seq_names)
    if isinstance(node, ast.BinOp):
        # sequence arithmetic obeys SIZE semantics, not integer bit
        # semantics: repetition multiplies (b'\x00' * 95 is 95 bytes,
        # not a 25-bit number), so it takes a literal, range-bounded
        # count — ('a' * 65000) * 65000 would otherwise slip a ~TB
        # allocation past an integer Mult bound
        left_seq = _may_be_sequence(node.left, seq_names)
        right_seq = _may_be_sequence(node.right, seq_names)
        if left_seq or right_seq:
            if isinstance(node.op, ast.Add) and left_seq and right_seq:
                return _bit_bound(node.left, seq_names) \
                    + _bit_bound(node.right, seq_names)
            if isinstance(node.op, ast.Mult) and (left_seq != right_seq):
                seq, count_node = ((node.left, node.right) if left_seq
                                   else (node.right, node.left))
                try:
                    count = _eval_literal(count_node)
                except ValueError:
                    raise ValueError("non-literal repetition count")
                if not isinstance(count, int) or not 0 <= count <= 4096:
                    raise ValueError("repetition count out of range")
                return _bit_bound(seq, seq_names) * max(count, 1)
            raise ValueError("unsupported sequence arithmetic")
        left = _bit_bound(node.left, seq_names)
        op = node.op
        if isinstance(op, (ast.Add, ast.Sub, ast.BitOr, ast.BitAnd,
                           ast.Mod, ast.FloorDiv, ast.RShift)):
            return max(left, _bit_bound(node.right, seq_names)) + 1
        if isinstance(op, ast.Mult):
            return left + _bit_bound(node.right, seq_names)
        if isinstance(op, (ast.Pow, ast.LShift)):
            try:
                exp = _eval_literal(node.right)
            except ValueError:
                raise ValueError("non-literal exponent/shift")
            if not isinstance(exp, int) or not 0 <= exp <= 4096:
                raise ValueError("exponent out of range")
            return left + exp if isinstance(op, ast.LShift) \
                else left * max(exp, 1)
    raise ValueError(f"unbounded node {type(node).__name__}")


def _check_safe_expr(expr: str,
                     extra_callees: frozenset = frozenset(),
                     seq_names: frozenset = frozenset()) -> None:
    """Gate for table cells emitted verbatim into the generated module
    (which is exec'd): only name/call/arithmetic expressions, no
    attribute access, subscripts, lambdas, comprehensions, or dunder
    names, and a composed magnitude bound (:func:`_bit_bound`).  Calls
    are restricted to the runtime cast whitelist (plus the build's
    spec-defined custom types): spec cells are name references and casts
    like ``uint64(2**3)`` or ``Bytes4('0x01000000')`` — a call to any
    other name (``eval``, ``pow``, …) is PUBLIC markdown trying to be
    code, so fail loud."""
    allowed_callees = _RUNTIME_CALLEES | extra_callees
    tree = ast.parse(expr, mode="eval")
    for node in ast.walk(tree):
        if not isinstance(node, _SAFE_EXPR_NODES):
            raise ValueError(
                f"constant cell {expr!r}: disallowed syntax "
                f"({type(node).__name__})")
        if isinstance(node, ast.Name) and node.id.startswith("_"):
            raise ValueError(
                f"constant cell {expr!r}: underscore name {node.id!r}")
        if isinstance(node, ast.Call):
            if not isinstance(node.func, ast.Name) \
                    or node.func.id not in allowed_callees:
                callee = (node.func.id if isinstance(node.func, ast.Name)
                          else type(node.func).__name__)
                raise ValueError(
                    f"constant cell {expr!r}: call to non-whitelisted "
                    f"callee {callee!r}")
    try:
        bits = _bit_bound(tree, seq_names)
    except ValueError as exc:
        raise ValueError(f"constant cell {expr!r}: {exc}")
    if bits > _MAX_CONST_BITS:
        raise ValueError(
            f"constant cell {expr!r}: magnitude bound {bits} bits "
            f"exceeds {_MAX_CONST_BITS}")


# custom-type cells are TYPE expressions: names and subscripted names
# with arithmetic index math (`ByteVector[A * B]`, `List[X, N]`) — no
# calls at all, unlike constant cells
_SAFE_TYPE_NODES = (
    ast.Expression, ast.Constant, ast.Name, ast.Load, ast.Subscript,
    ast.Tuple, ast.BinOp, ast.Add, ast.Sub, ast.Mult, ast.Pow,
    ast.FloorDiv, ast.LShift,
)


def _check_safe_type_expr(expr: str) -> None:
    """Gate for custom-type table cells ('SSZ equivalent' column),
    which emit verbatim into the exec'd module exactly like constant
    cells do: same untrusted-markdown channel, same treatment.  Type
    grammar only — any Call, attribute access, or unbounded index
    arithmetic fails loud."""
    tree = ast.parse(expr, mode="eval")
    for node in ast.walk(tree):
        if not isinstance(node, _SAFE_TYPE_NODES):
            raise ValueError(
                f"custom-type cell {expr!r}: disallowed syntax "
                f"({type(node).__name__})")
        if isinstance(node, ast.Name) and node.id.startswith("_"):
            raise ValueError(
                f"custom-type cell {expr!r}: underscore name {node.id!r}")
    try:
        bits = _bit_bound(tree)
    except ValueError as exc:
        raise ValueError(f"custom-type cell {expr!r}: {exc}")
    if bits > _MAX_CONST_BITS:
        raise ValueError(
            f"custom-type cell {expr!r}: magnitude bound {bits} bits "
            f"exceeds {_MAX_CONST_BITS}")


def _const_rhs(expr: str,
               extra_callees: frozenset = frozenset(),
               seq_names: frozenset = frozenset()) -> str:
    """Right-hand side for a constant: simple literals collapse to their
    value; anything referencing other names (uint64(...), 10 * BASE) is
    emitted after passing the :func:`_check_safe_expr` whitelist and
    evaluates in the generated module's namespace, where the runtime
    types and earlier constants are in scope."""
    value = parse_value(expr)
    if isinstance(value, str) and value == expr.strip().strip("`"):
        _check_safe_expr(value, extra_callees, seq_names)
        return value        # unresolvable here: defer to module namespace
    return repr(value)


def _collect_seq_names(spec) -> frozenset:
    """Names this build binds to SEQUENCE values (bytes, strings,
    tuples, lists): custom types that resolve (transitively) to
    Bytes*/ByteVector/ByteList, plus constants whose cell is a
    string/tuple/list literal, a byte-typed cast, or a reference/
    concatenation of other sequence names.  Fixpoint because constants
    reference each other."""
    seq_names: set = set()
    changed = True
    while changed:
        changed = False
        for name, texpr in spec.custom_types.items():
            if name in seq_names:
                continue
            root = texpr.split("[")[0].strip()
            if root.startswith(("Bytes", "ByteVector", "ByteList")) \
                    or root in seq_names:
                seq_names.add(name)
                changed = True
        for name, expr in {**spec.preset_vars,
                           **spec.constants}.items():
            if name in seq_names:
                continue
            cell = str(expr).strip().strip("`")
            try:
                body = ast.parse(cell, mode="eval").body
            except SyntaxError:
                continue
            # _may_be_sequence covers every cell shape: literals
            # (str/bytes/tuple/list), byte casts, aliases of and
            # arithmetic over already-known sequence names
            if _may_be_sequence(body, frozenset(seq_names)):
                seq_names.add(name)
                changed = True
    return frozenset(seq_names)


def _dependency_order(defs: dict) -> list:
    """Order name->rhs definitions so referenced names precede their
    users; ties keep input order, unresolvable cycles fall back to input
    order."""
    names = set(defs)
    deps = {n: {m for m in re.findall(r"\b(\w+)\b", rhs)
                if m in names and m != n}
            for n, rhs in defs.items()}
    ordered, done = [], set()
    while len(ordered) < len(defs):
        progress = False
        for name in defs:
            if name in done:
                continue
            if deps[name] <= done:
                ordered.append(name)
                done.add(name)
                progress = True
        if not progress:
            for name in defs:
                if name not in done:
                    ordered.append(name)
                    done.add(name)
    return ordered


def dependency_order_classes(classes: dict) -> list:
    """Order class sources so every referenced spec class precedes its
    users (fixpoint over referenced names, reference helpers.py:201)."""
    names = set(classes)
    deps = {}
    for name, src in classes.items():
        body = src.split("\n", 1)[1] if "\n" in src else ""
        deps[name] = {m for m in re.findall(r"\b([A-Z]\w*)\b", body)
                      if m in names and m != name}
    ordered, done = [], set()
    while len(ordered) < len(classes):
        progress = False
        for name in sorted(classes):
            if name in done:
                continue
            if deps[name] <= done:
                ordered.append(name)
                done.add(name)
                progress = True
        if not progress:           # cycle: emit remaining alphabetically
            for name in sorted(names - done):
                ordered.append(name)
                done.add(name)
    return ordered


def emit_source(spec: ParsedSpec, preset: dict | None = None,
                config: dict | None = None,
                prelude: str = "",
                extra_scalars: dict | None = None,
                class_subs: list | None = None,
                epilogue: str = "") -> str:
    """Assemble the module source: header, types, constants, classes,
    prelude, functions, config.  `preset` overrides preset-var values
    (compile-time tier); `config` overrides config-var values (runtime
    tier); `prelude` is fork-injected code (engine stubs, trusted
    setups — compiler/forks.py); `class_subs` are (pattern, repl) regex
    rewrites applied to CLASS BODIES only (e.g. eip6800's nullable
    `Optional[X]` fields becoming SSZ `Union[None, X]` without touching
    typing.Optional in function annotations)."""
    parts = [_HEADER]

    # names the prelude defines (e.g. the KZG trusted-setup vectors, whose
    # markdown table cells describe the TYPE, not a value — the reference
    # inlines real data there too, setup.py:190-195)
    prelude_names: set = set()
    for m in re.finditer(r"^([A-Za-z_0-9 ,]+?)\s*=", prelude or "", re.M):
        for tok in m.group(1).split(","):
            if tok.strip().isidentifier():
                prelude_names.add(tok.strip())

    # presets, custom types and constants reference each other in both
    # directions (Transaction = ByteList[MAX_BYTES_PER_TRANSACTION];
    # GENESIS_SLOT = Slot(0); Blob = ByteVector[BYTES_PER_FIELD_ELEMENT *
    # FIELD_ELEMENTS_PER_BLOB]) — emit them in one dependency-ordered
    # fixpoint, like the class ordering below
    preset = dict(preset or {})
    # spec-defined custom types (Slot, Epoch, Gwei, DomainType, …) are
    # legitimate cast targets in constant cells; prelude-defined names
    # are trusted repo code (fork builders), not markdown
    cell_callees = frozenset(spec.custom_types) | frozenset(prelude_names)
    # type knowledge for the repetition guard: which names hold
    # sequences (repeating those multiplies size — _may_be_sequence)
    seq_names = _collect_seq_names(spec)
    scalars: dict[str, str] = {}
    for name, expr in spec.preset_vars.items():
        if name not in prelude_names:
            scalars[name] = (repr(preset[name]) if name in preset
                             else _const_rhs(expr, cell_callees,
                                             seq_names))
    for name, type_expr in spec.custom_types.items():
        _check_safe_type_expr(type_expr)
        scalars[name] = type_expr
    for name, expr in spec.constants.items():
        if name in prelude_names:
            continue
        if expr.strip().rstrip("*") in ("TBD", "N/A"):
            # draft placeholder (e.g. whisk's CURDLEPROOFS_CRS) — a
            # definition must come from extra_scalars or the prelude
            continue
        scalars[name] = _const_rhs(expr, cell_callees, seq_names)
    for name, rhs in (extra_scalars or {}).items():
        scalars.setdefault(name, rhs)

    for name in _dependency_order(scalars):
        parts.append(f"{name} = {scalars[name]}")

    # preludes precede the class definitions: class-body annotations
    # evaluate eagerly, so rebindings like eip6800's SSZ Optional must
    # already be in scope when the containers build
    if prelude:
        parts.append(prelude.strip())

    for name in dependency_order_classes(spec.classes):
        src = spec.classes[name]
        for pattern, repl in (class_subs or []):
            src = re.sub(pattern, repl, src)
        parts.append(src)

    # runtime-config tier: bare config-var references inside function
    # bodies are rewritten to `config.X` so tests can swap configurations
    # without re-emitting the module (the reference's regex rewrite,
    # pysetup/helpers.py:83-102)
    cfg_names = sorted(spec.config_vars, key=len, reverse=True)
    cfg_re = (re.compile(r"\b(" + "|".join(cfg_names) + r")\b")
              if cfg_names else None)

    def _cfg(src: str) -> str:
        return cfg_re.sub(lambda m: f"config.{m.group(1)}", src) \
            if cfg_re is not None else src

    # protocol classes from `self:`-typed markdown functions (reference
    # setup.py:234-241 / pysetup emission): abstract methods stay `...`,
    # concrete bodies (e.g. verify_and_notify_new_payload's empty-
    # transaction check) are REAL spec code the engine epilogue inherits.
    # Emitted before the free functions because parameter annotations
    # (`engine: ExecutionEngine`) evaluate at def time.
    for pname in sorted(spec.protocols):
        body = "\n\n".join(
            # `self: Name` -> `self`: the annotation would evaluate
            # inside the class body where the name doesn't exist yet
            # (reference helpers.py:66 does the same replace)
            textwrap.indent(_cfg(src).replace(f"self: {pname}", "self"),
                            "    ")
            for _fn, src in spec.protocols[pname].items())
        parts.append(f"class {pname}(Protocol):\n{body}")

    # fork epilogues subclass the extracted protocols (the noop engine,
    # reference execution_engine_cls injection)
    if epilogue:
        parts.append(epilogue.strip())

    for name, src in spec.functions.items():
        parts.append(_cfg(src))

    config = dict(config or {})
    cfg_items = ", ".join(
        f"{k}={config[k]!r}" if k in config else f"{k}={parse_value(v)!r}"
        for k, v in spec.config_vars.items())
    parts.append("from consensus_specs_tpu.compiler.builder import Config")
    parts.append(f"config = Config({cfg_items})")

    return "\n\n\n".join(parts) + "\n"


# import roots a generated module may touch: its header + fork preludes
# import only the runtime package, dataclasses and typing
_ALLOWED_IMPORT_ROOTS = ("consensus_specs_tpu", "dataclasses", "typing")


def _guarded_import(name, globals=None, locals=None, fromlist=(), level=0):
    if level == 0 and name.split(".")[0] not in _ALLOWED_IMPORT_ROOTS:
        raise ImportError(
            f"generated spec module may not import {name!r}")
    return __import__(name, globals, locals, fromlist, level)


# builtins reachable from a generated module.  Everything spec markdown
# legitimately uses (casts, container ops, arithmetic, exceptions, the
# class machinery) minus the escape hatches: no eval/exec/compile, no
# open/input/breakpoint, no vars/globals/locals/delattr/setattr, and
# __import__ is root-whitelisted.  This is the exec-side half of the
# constant-cell gate: even an expression that slipped the static check
# finds no dangerous callable at module-exec time.
_SAFE_BUILTIN_NAMES = (
    "abs", "all", "any", "bin", "bool", "bytearray", "bytes", "callable",
    "chr", "classmethod", "dict", "divmod", "enumerate", "filter",
    "float", "format", "frozenset", "getattr", "hasattr", "hash", "hex",
    "id", "int", "isinstance", "issubclass", "iter", "len", "list",
    "map", "max", "min", "next", "object", "oct", "ord", "pow", "print",
    "property", "range", "repr", "reversed", "round", "set", "slice",
    "sorted", "staticmethod", "str", "sum", "super", "tuple", "type",
    "zip",
    "ArithmeticError", "AssertionError", "AttributeError",
    "BaseException", "Exception", "IndexError", "KeyError", "KeyboardInterrupt",
    "NotImplementedError", "OverflowError", "RecursionError",
    "RuntimeError", "StopIteration", "TypeError", "ValueError",
    "ZeroDivisionError", "NotImplemented", "Ellipsis",
    "True", "False", "None",
)


def _restricted_builtins() -> dict:
    import builtins as _b
    safe = {n: getattr(_b, n) for n in _SAFE_BUILTIN_NAMES
            if hasattr(_b, n)}
    safe["__import__"] = _guarded_import
    safe["__build_class__"] = _b.__build_class__
    safe["__name__"] = "builtins"
    return safe


def build_spec(doc_texts: list, preset: dict | None = None,
               config: dict | None = None,
               module_name: str = "generated_spec",
               prelude: str = "",
               extra_scalars: dict | None = None,
               class_subs: list | None = None,
               epilogue: str = ''):
    """Parse + merge fork markdown docs (oldest first) and exec the module.

    Returns (module, source).
    """
    merged = ParsedSpec()
    for text in doc_texts:
        merged = parse_markdown(text).merge_over(merged)
    source = emit_source(merged, preset, config, prelude,
                         extra_scalars, class_subs, epilogue)
    module = types.ModuleType(module_name)
    # dont_inherit: this builder's __future__ flags (stringified
    # annotations) must not leak into the generated module — SSZ field
    # annotations have to stay live class objects.  Restricted builtins:
    # markdown-derived code execs without eval/exec/open/__import__
    # escape hatches (see _restricted_builtins)
    module.__dict__["__builtins__"] = _restricted_builtins()
    exec(compile(source, f"<{module_name}>", "exec", dont_inherit=True),
         module.__dict__)
    return module, source
