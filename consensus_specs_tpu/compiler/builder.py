"""Spec builder: merge parsed fork docs and emit an executable module.

Capability counterpart of the reference's pysetup pipeline
(/root/reference/pysetup/helpers.py:37-273 `objects_to_spec`,
`combine_spec_objects`, `dependency_order_class_objects` and
setup.py:373 `build_spec`):

- fork docs merge in order, newer definitions override older ones
- SSZ container classes are emitted in field-dependency fixpoint order
- preset vars bake in as module constants (shape-defining, compile-time)
- config vars land in a mutable `config` namespace (runtime-swappable,
  the reference's two-tier preset/config split)
- the emitted source execs against our runtime (ssz types, bls shim,
  hash) into a real module object
"""
from __future__ import annotations

import re
import types

from .parser import ParsedSpec, parse_markdown, parse_value

_HEADER = '''\
"""GENERATED spec module — consensus_specs_tpu.compiler output."""
from dataclasses import dataclass, field
from consensus_specs_tpu.ssz import (
    boolean, uint8, uint16, uint32, uint64, uint128, uint256,
    Bitlist, Bitvector, ByteList, ByteVector, List, Vector, Container,
    Union, Bytes1, Bytes4, Bytes8, Bytes20, Bytes31, Bytes32, Bytes48,
    Bytes96, hash_tree_root, serialize,
)
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.hash import hash
'''


class Config(types.SimpleNamespace):
    """Runtime-swappable config namespace."""


def _const_rhs(expr: str) -> str:
    """Right-hand side for a constant: simple literals collapse to their
    value; anything referencing other names (uint64(...), 10 * BASE) is
    emitted verbatim and evaluates in the generated module's namespace,
    where the runtime types and earlier constants are in scope."""
    value = parse_value(expr)
    if isinstance(value, str) and value == expr.strip().strip("`"):
        return value        # unresolvable here: defer to module namespace
    return repr(value)


def dependency_order_classes(classes: dict) -> list:
    """Order class sources so every referenced spec class precedes its
    users (fixpoint over referenced names, reference helpers.py:201)."""
    names = set(classes)
    deps = {}
    for name, src in classes.items():
        body = src.split("\n", 1)[1] if "\n" in src else ""
        deps[name] = {m for m in re.findall(r"\b([A-Z]\w*)\b", body)
                      if m in names and m != name}
    ordered, done = [], set()
    while len(ordered) < len(classes):
        progress = False
        for name in sorted(classes):
            if name in done:
                continue
            if deps[name] <= done:
                ordered.append(name)
                done.add(name)
                progress = True
        if not progress:           # cycle: emit remaining alphabetically
            for name in sorted(names - done):
                ordered.append(name)
                done.add(name)
    return ordered


def emit_source(spec: ParsedSpec, preset: dict | None = None) -> str:
    """Assemble the module source: header, types, constants, classes,
    functions, config."""
    parts = [_HEADER]

    for name, type_expr in spec.custom_types.items():
        parts.append(f"{name} = {type_expr}")

    preset = dict(preset or {})
    for name, expr in spec.preset_vars.items():
        if name in preset:
            parts.append(f"{name} = {preset[name]!r}")
        else:
            parts.append(f"{name} = {_const_rhs(expr)}")
    for name, expr in spec.constants.items():
        parts.append(f"{name} = {_const_rhs(expr)}")

    for name in dependency_order_classes(spec.classes):
        parts.append(spec.classes[name])

    for name, src in spec.functions.items():
        parts.append(src)

    cfg_items = ", ".join(
        f"{k}={parse_value(v)!r}" for k, v in spec.config_vars.items())
    parts.append("from consensus_specs_tpu.compiler.builder import Config")
    parts.append(f"config = Config({cfg_items})")

    return "\n\n\n".join(parts) + "\n"


def build_spec(doc_texts: list, preset: dict | None = None,
               module_name: str = "generated_spec"):
    """Parse + merge fork markdown docs (oldest first) and exec the module.

    Returns (module, source).
    """
    merged = ParsedSpec()
    for text in doc_texts:
        merged = parse_markdown(text).merge_over(merged)
    source = emit_source(merged, preset)
    module = types.ModuleType(module_name)
    # dont_inherit: this builder's __future__ flags (stringified
    # annotations) must not leak into the generated module — SSZ field
    # annotations have to stay live class objects
    exec(compile(source, f"<{module_name}>", "exec", dont_inherit=True),
         module.__dict__)
    return module, source
