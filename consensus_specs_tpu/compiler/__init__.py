"""Markdown spec compiler (the reference's L2 layer).

Turns markdown spec documents — fenced python blocks, constant/preset/
config tables — into executable modules wired to the framework runtime,
with fork-overlay merging and dependency-ordered SSZ class emission.
"""
from .parser import parse_markdown, parse_value, ParsedSpec  # noqa: F401
from .builder import build_spec, emit_source, Config  # noqa: F401
