"""Per-fork build knowledge: doc chains and injected preludes.

Capability counterpart of the reference's per-fork spec builders
(pysetup/spec_builders/*.py and pysetup/md_doc_paths.py:79-97): each fork
names the markdown docs that feed its build and a prelude injected between
the SSZ classes and the functions — execution-engine stubs, KZG trusted
setup, and other symbols the reference wires in via imports.
"""
from __future__ import annotations

import os

FORK_CHAIN = ["phase0", "altair", "bellatrix", "capella", "deneb",
              "electra", "fulu"]

# docs contributed BY each fork (ancestors' docs are prepended)
FORK_DOCS = {
    "phase0": ["beacon-chain.md"],
    "altair": ["beacon-chain.md", "bls.md"],
    "bellatrix": ["beacon-chain.md"],
    "capella": ["beacon-chain.md"],
    "deneb": ["polynomial-commitments.md", "beacon-chain.md"],
    "electra": ["beacon-chain.md"],
    "fulu": ["polynomial-commitments-sampling.md", "das-core.md",
             "beacon-chain.md"],
}

# the bellatrix execution-engine protocol: the spec treats the EL as an
# opaque boundary; tests run against a noop engine answering True
# (reference pysetup/spec_builders/bellatrix.py:39-64, deneb.py:48-80)
_ENGINE_PRELUDE = '''
class ExecutionEngine:
    """Noop execution engine: the EL process boundary, stubbed."""

    def notify_new_payload(self, *args, **kwargs) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return True

    def notify_forkchoice_updated(self, *args, **kwargs):
        return None

    def get_payload(self, payload_id):
        raise NotImplementedError("no payload building in the noop engine")

    def is_valid_block_hash(self, *args, **kwargs) -> bool:
        return True

    def is_valid_versioned_hashes(self, *args, **kwargs) -> bool:
        return True


NoopExecutionEngine = ExecutionEngine

EXECUTION_ENGINE = NoopExecutionEngine()
'''

# deneb trusted setup: the reference inlines the JSON into the generated
# module (setup.py:190-195); we load it through the runtime at import time
_KZG_PRELUDE = '''
from consensus_specs_tpu.compiler.forks import load_kzg_trusted_setup as \\
    _load_kzg_trusted_setup

KZG_SETUP_G1_MONOMIAL, KZG_SETUP_G1_LAGRANGE, KZG_SETUP_G2_MONOMIAL = \\
    _load_kzg_trusted_setup()
'''

FORK_PRELUDES = {
    "bellatrix": _ENGINE_PRELUDE,
    "deneb": _KZG_PRELUDE,
}

# constants a fork's class shapes need that live in docs outside its build
# chain (e.g. fulu's inclusion-proof depth is "predefined" in
# p2p-interface.md) — injected into the scalar-definition fixpoint
FORK_SCALARS = {
    "fulu": {
        # floorlog2(get_generalized_index(BeaconBlockBody,
        # 'blob_kzg_commitments')): predefined in fulu/p2p-interface.md
        "KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH": "uint64(4)",
        # discovery-layer type (phase0/p2p-interface.md custom types)
        "NodeID": "uint256",
    },
}


def load_kzg_trusted_setup():
    """(G1 monomial, G1 lagrange, G2 monomial) as bytes48/bytes96 tuples."""
    import json
    path = os.path.join(os.path.dirname(__file__), "..", "config",
                        "trusted_setups", "trusted_setup_4096.json")
    with open(path) as f:
        ts = json.load(f)
    return (tuple(bytes.fromhex(h[2:]) for h in ts["g1_monomial"]),
            tuple(bytes.fromhex(h[2:]) for h in ts["g1_lagrange"]),
            tuple(bytes.fromhex(h[2:]) for h in ts["g2_monomial"]))


def doc_paths(specs_dir: str, fork: str) -> list:
    """Full doc chain for `fork`: ancestor docs oldest-first."""
    chain = FORK_CHAIN[: FORK_CHAIN.index(fork) + 1]
    out = []
    for f in chain:
        for doc in FORK_DOCS.get(f, []):
            p = os.path.join(specs_dir, f, doc)
            if os.path.exists(p):
                out.append(p)
    return out


def fork_prelude(fork: str) -> str:
    """Concatenated preludes of the fork and its ancestors."""
    chain = FORK_CHAIN[: FORK_CHAIN.index(fork) + 1]
    return "\n".join(FORK_PRELUDES[f] for f in chain
                     if f in FORK_PRELUDES)


def fork_scalars(fork: str) -> dict:
    """Merged injected scalar definitions for the fork chain."""
    chain = FORK_CHAIN[: FORK_CHAIN.index(fork) + 1]
    out: dict = {}
    for f in chain:
        out.update(FORK_SCALARS.get(f, {}))
    return out
