"""Per-fork build knowledge: doc chains and injected preludes.

Capability counterpart of the reference's per-fork spec builders
(pysetup/spec_builders/*.py and pysetup/md_doc_paths.py:79-97): each fork
names the markdown docs that feed its build and a prelude injected
BEFORE the SSZ classes (class-body annotations evaluate eagerly, so
rebinds the classes rely on must already be in scope) — execution-engine
stubs, KZG trusted setup, the whisk curdleproofs shim, and other symbols
the reference wires in via imports.  Preludes must not reference spec
containers at top level; those only exist later in the module.
"""
from __future__ import annotations

import os

FORK_CHAIN = ["phase0", "altair", "bellatrix", "capella", "deneb",
              "electra", "fulu"]

# feature forks branch off the mainline (reference
# pysetup/md_doc_paths.py:17-28 PREVIOUS_FORK_OF)
PREVIOUS_FORK = {"whisk": "capella", "eip7732": "electra",
                 "eip6800": "deneb"}
FEATURE_DIRS = {f: os.path.join("_features", f) for f in PREVIOUS_FORK}


def chain_of(fork: str) -> list:
    """Doc-chain fork names oldest-first (mainline prefix + feature)."""
    if fork in PREVIOUS_FORK:
        base = PREVIOUS_FORK[fork]
        return FORK_CHAIN[: FORK_CHAIN.index(base) + 1] + [fork]
    return FORK_CHAIN[: FORK_CHAIN.index(fork) + 1]

# docs contributed BY each fork (ancestors' docs are prepended)
FORK_DOCS = {
    "phase0": ["beacon-chain.md"],
    "altair": ["beacon-chain.md", "bls.md"],
    "bellatrix": ["beacon-chain.md"],
    "capella": ["beacon-chain.md"],
    "deneb": ["polynomial-commitments.md", "beacon-chain.md"],
    "electra": ["beacon-chain.md"],
    "fulu": ["polynomial-commitments-sampling.md", "das-core.md",
             "beacon-chain.md"],
    "whisk": ["beacon-chain.md"],
    "eip7732": ["beacon-chain.md"],
    "eip6800": ["beacon-chain.md"],
}

# the bellatrix execution-engine boundary: the ExecutionEngine Protocol
# class itself is now EXTRACTED from the markdown's `self:`-typed
# functions (compiler/parser.py _SELF_TYPE_RE, like reference
# setup.py:234-241), so the injected code is only what the reference's
# builders inject too: the noop engine instance
# (pysetup/spec_builders/bellatrix.py:39-64, deneb.py:48-80 — note the
# reference Noop OVERRIDES verify_and_notify_new_payload to plain True,
# it does not inherit the protocol body; match that)
_ENGINE_EPILOGUE = '''
class NoopExecutionEngine(ExecutionEngine):
    """Noop execution engine: the EL process boundary, stubbed
    (answers True to every verification, builds no payloads)."""

    def notify_new_payload(self, *args, **kwargs) -> bool:
        return True

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return True

    def notify_forkchoice_updated(self, *args, **kwargs):
        return None

    def get_payload(self, payload_id):
        raise NotImplementedError("no payload building in the noop engine")

    def is_valid_block_hash(self, *args, **kwargs) -> bool:
        return True

    def is_valid_versioned_hashes(self, *args, **kwargs) -> bool:
        return True


EXECUTION_ENGINE = NoopExecutionEngine()
'''

# deneb trusted setup: the reference inlines the JSON into the generated
# module (setup.py:190-195); we load it through the runtime at import time
_KZG_PRELUDE = '''
from consensus_specs_tpu.compiler.forks import load_kzg_trusted_setup as \\
    _load_kzg_trusted_setup

KZG_SETUP_G1_MONOMIAL, KZG_SETUP_G1_LAGRANGE, KZG_SETUP_G2_MONOMIAL = \\
    _load_kzg_trusted_setup()
'''

# whisk: the markdown calls the external curdleproofs verifiers
# (whisk/beacon-chain.md:105-128); route them to our from-scratch proof
# system behind the same interface
_WHISK_PRELUDE = """
class _Curdleproofs:
    @staticmethod
    def IsValidWhiskShuffleProof(crs, pre_trackers, post_trackers,
                                 shuffle_proof):
        from consensus_specs_tpu.crypto import whisk_proofs
        return whisk_proofs.verify_shuffle(
            [(bytes(t.r_G), bytes(t.k_r_G)) for t in pre_trackers],
            [(bytes(t.r_G), bytes(t.k_r_G)) for t in post_trackers],
            bytes(shuffle_proof))

    @staticmethod
    def IsValidWhiskOpeningProof(tracker, k_commitment, tracker_proof):
        from consensus_specs_tpu.crypto import whisk_proofs
        return whisk_proofs.verify_opening(
            bytes(tracker.r_G), bytes(tracker.k_r_G),
            bytes(k_commitment), bytes(tracker_proof))


curdleproofs = _Curdleproofs()
"""

FORK_PRELUDES = {
    "deneb": _KZG_PRELUDE,
    "whisk": _WHISK_PRELUDE,
}

# epilogues land AFTER the extracted Protocol classes (they subclass
# them) and before the free functions
FORK_EPILOGUES = {
    "bellatrix": _ENGINE_EPILOGUE,
}

# class-body-only regex rewrites: eip6800 container fields use
# Optional[X] for nullable values (eip6800/beacon-chain.md
# SuffixStateDiff), which is SSZ Union[None, X]; scoping the rewrite to
# class bodies leaves typing.Optional in function annotations intact
FORK_CLASS_SUBS = {
    "eip6800": [(r"\bOptional\[", "Union[None, ")],
}


def fork_class_subs(fork: str) -> list:
    out: list = []
    for f in chain_of(fork):
        out.extend(FORK_CLASS_SUBS.get(f, []))
    return out

# constants a fork's class shapes need that live in docs outside its build
# chain (e.g. fulu's inclusion-proof depth is "predefined" in
# p2p-interface.md) — injected into the scalar-definition fixpoint
FORK_SCALARS = {
    "whisk": {
        # "TBD" in the markdown constants table; our verifier carries
        # its own parameters, the CRS slot just needs to exist
        "CURDLEPROOFS_CRS": "None",
    },
    "fulu": {
        # floorlog2(get_generalized_index(BeaconBlockBody,
        # 'blob_kzg_commitments')): predefined in fulu/p2p-interface.md
        "KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH": "uint64(4)",
        # discovery-layer type (phase0/p2p-interface.md custom types)
        "NodeID": "uint256",
    },
}


class MissingDocs(FileNotFoundError):
    """No markdown docs found for a fork (distinct from other
    FileNotFoundErrors raised during the build, e.g. a missing trusted
    setup — callers skipping absent docs must not swallow those)."""


def build_fork(specs_dir: str, fork: str, preset_name: str,
               module_name: str | None = None):
    """THE fork-build recipe (doc chain + prelude + scalars + class
    subs + preset/config): shared by scripts/build_pyspec.py and the
    compiler tests so they cannot drift.  Returns (module, source)."""
    from .builder import build_spec
    from ..config import load_config, load_preset
    paths = doc_paths(specs_dir, fork)
    if not paths:
        raise MissingDocs(f"no docs for fork {fork!r} under {specs_dir}")
    return build_spec(
        [open(p).read() for p in paths],
        preset=load_preset(preset_name),
        config=load_config(preset_name).as_dict(),
        module_name=module_name or f"{fork}_{preset_name}",
        prelude=fork_prelude(fork),
        extra_scalars=fork_scalars(fork),
        class_subs=fork_class_subs(fork),
        epilogue=fork_epilogue(fork))


def load_kzg_trusted_setup():
    """(G1 monomial, G1 lagrange, G2 monomial) as bytes48/bytes96 tuples."""
    import json
    path = os.path.join(os.path.dirname(__file__), "..", "config",
                        "trusted_setups", "trusted_setup_4096.json")
    with open(path) as f:
        ts = json.load(f)
    return (tuple(bytes.fromhex(h[2:]) for h in ts["g1_monomial"]),
            tuple(bytes.fromhex(h[2:]) for h in ts["g1_lagrange"]),
            tuple(bytes.fromhex(h[2:]) for h in ts["g2_monomial"]))


def doc_paths(specs_dir: str, fork: str) -> list:
    """Full doc chain for `fork`: ancestor docs oldest-first."""
    out = []
    for f in chain_of(fork):
        subdir = FEATURE_DIRS.get(f, f)
        for doc in FORK_DOCS.get(f, []):
            p = os.path.join(specs_dir, subdir, doc)
            if os.path.exists(p):
                out.append(p)
    return out


def fork_prelude(fork: str) -> str:
    """Concatenated preludes of the fork and its ancestors."""
    return "\n".join(FORK_PRELUDES[f] for f in chain_of(fork)
                     if f in FORK_PRELUDES)


def fork_epilogue(fork: str) -> str:
    """Concatenated epilogues of the fork and its ancestors."""
    return "\n".join(FORK_EPILOGUES[f] for f in chain_of(fork)
                      if f in FORK_EPILOGUES)


def fork_scalars(fork: str) -> dict:
    """Merged injected scalar definitions for the fork chain."""
    out: dict = {}
    for f in chain_of(fork):
        out.update(FORK_SCALARS.get(f, {}))
    return out
