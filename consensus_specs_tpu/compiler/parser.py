"""Markdown spec parser: headings, fenced python blocks, constant tables.

Capability counterpart of the reference's marko-based extractor
(/root/reference/setup.py:203-341 `get_spec`), built as a small
line-oriented GFM subset parser (no external markdown dependency):

- ```python fenced blocks become functions (`def name`), SSZ container
  classes (`class X(Container)`), or dataclasses
- two-column tables `| Name | Value |` become constants; a table under a
  heading containing "preset" contributes preset vars, under "config"
  runtime config vars, otherwise plain constants
- `<!-- skip -->` immediately before a block excludes it
- custom-type tables `| Name | SSZ equivalent | ... |` become type aliases
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field


@dataclass
class ParsedSpec:
    functions: dict = field(default_factory=dict)     # name -> source
    classes: dict = field(default_factory=dict)       # name -> source
    constants: dict = field(default_factory=dict)     # name -> value expr
    preset_vars: dict = field(default_factory=dict)
    config_vars: dict = field(default_factory=dict)
    custom_types: dict = field(default_factory=dict)  # name -> type expr
    # `self: Type`-typed markdown functions become Protocol-class
    # methods (reference setup.py:234-241): class name -> {fn -> source}
    protocols: dict = field(default_factory=dict)

    def merge_over(self, older: "ParsedSpec") -> "ParsedSpec":
        """This spec layered on top of `older` (newer definitions win)."""
        protocols = {name: dict(fns)
                     for name, fns in older.protocols.items()}
        for name, fns in self.protocols.items():
            protocols.setdefault(name, {}).update(fns)
        out = ParsedSpec(
            functions={**older.functions, **self.functions},
            classes={**older.classes, **self.classes},
            constants={**older.constants, **self.constants},
            preset_vars={**older.preset_vars, **self.preset_vars},
            config_vars={**older.config_vars, **self.config_vars},
            custom_types={**older.custom_types, **self.custom_types},
            protocols=protocols,
        )
        return out


_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
# anchored per-line: decorators (@dataclass etc.) may precede the keyword
_DEF_RE = re.compile(r"^def\s+(\w+)", re.M)
_CLASS_RE = re.compile(r"^class\s+(\w+)", re.M)
# first parameter `self: Type` marks a Protocol method
_SELF_TYPE_RE = re.compile(r"^def\s+(\w+)\(\s*self:\s*(\w+)", re.M)


def _table_rows(lines, start):
    """Parse a GFM table starting at `start`; returns (rows, end_index)."""
    rows = []
    i = start
    while i < len(lines) and lines[i].strip().startswith("|"):
        cells = [c.strip() for c in lines[i].strip().strip("|").split("|")]
        rows.append(cells)
        i += 1
    return rows, i


def _is_separator_row(cells) -> bool:
    return all(re.fullmatch(r":?-+:?", c) or c == "" for c in cells)


def _cell_expr(cell: str) -> str:
    """Extract the code expression from a table cell: the first
    backtick-delimited token if present (real spec cells read
    '`uint64(2**6)` (= 64)'), else the raw cell."""
    m = re.search(r"`([^`]+)`", cell)
    return m.group(1) if m else cell.strip()


def parse_markdown(text: str) -> ParsedSpec:
    spec = ParsedSpec()
    lines = text.split("\n")
    # heading STACK so tables under '### Misc' inside '## Preset' classify
    # by the full path (the real specs nest their preset/config tables)
    heading_stack: list[tuple[int, str]] = []
    skip_next = False
    i = 0
    while i < len(lines):
        line = lines[i]
        stripped = line.strip()

        if stripped.startswith("#"):
            level = len(stripped) - len(stripped.lstrip("#"))
            text_part = stripped.lstrip("#").strip().lower()
            while heading_stack and heading_stack[-1][0] >= level:
                heading_stack.pop()
            heading_stack.append((level, text_part))
            i += 1
            continue

        if stripped == "<!-- skip -->":
            skip_next = True
            i += 1
            continue

        if stripped.startswith("```python"):
            j = i + 1
            block = []
            while j < len(lines) and not lines[j].strip().startswith("```"):
                block.append(lines[j])
                j += 1
            source = "\n".join(block).rstrip()
            if not skip_next and source:
                m = _CLASS_RE.search(source)
                f = _DEF_RE.search(source)
                if m and (not f or m.start() < f.start()):
                    spec.classes[m.group(1)] = source
                elif f:
                    s = _SELF_TYPE_RE.search(source)
                    if s:
                        spec.protocols.setdefault(
                            s.group(2), {})[s.group(1)] = source
                    else:
                        spec.functions[f.group(1)] = source
            skip_next = False
            i = j + 1
            continue

        if stripped.startswith("|"):
            rows, end = _table_rows(lines, i)
            i = end
            if skip_next:
                skip_next = False
                continue
            if len(rows) >= 2 and _is_separator_row(rows[1]):
                header = [h.lower() for h in rows[0]]
                body = rows[2:]
                path = " / ".join(t for _, t in heading_stack)
                if len(header) >= 2 and "ssz equivalent" in header[1]:
                    for cells in body:
                        if len(cells) >= 2 and cells[0]:
                            spec.custom_types[_cell_expr(cells[0])] = \
                                _cell_expr(cells[1])
                elif len(header) >= 2 and header[0] == "name":
                    target = spec.constants
                    if "preset" in path:
                        target = spec.preset_vars
                    elif "config" in path:
                        target = spec.config_vars
                    for cells in body:
                        if len(cells) < 2:
                            continue
                        name = _cell_expr(cells[0])
                        if _NAME_RE.match(name):
                            target[name] = _cell_expr(cells[1])
            continue

        i += 1
    return spec


_LITERAL_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Pow: lambda a, b: a ** b,
    ast.FloorDiv: lambda a, b: a // b,
}


def _eval_literal(node: "ast.AST"):
    """Whitelist evaluator for constant-table cells.  Accepts only
    int/str/bytes literals, unary minus, and +,-,*,**,// over those —
    the grammar the spec tables actually use (`2**11`, `16 * 2**10`,
    `4096`, `0x01`, `'BLS_SIG...'`).  Anything else (names, calls,
    attribute access) raises, so markdown cells can never reach
    attribute-walk escapes the way a bare ``eval`` could."""
    if isinstance(node, ast.Expression):
        return _eval_literal(node.body)
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, str, bytes)):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        operand = _eval_literal(node.operand)
        if isinstance(operand, int):
            return -operand
        raise ValueError("unary minus on non-int")
    if isinstance(node, ast.BinOp) and type(node.op) in _LITERAL_BINOPS:
        left = _eval_literal(node.left)
        right = _eval_literal(node.right)
        if isinstance(left, int) and isinstance(right, int):
            if isinstance(node.op, ast.Pow) and (
                    right > 4096 or abs(left) > 1 << 64):
                raise ValueError("exponent out of range")
            return _LITERAL_BINOPS[type(node.op)](left, right)
        raise ValueError("arithmetic on non-ints")
    raise ValueError(f"disallowed literal node {type(node).__name__}")


def parse_value(expr: str):
    """Evaluate a constant cell: ints (any base, `2**n`, `10 * 2**10`),
    hex byte strings, quoted strings.  Uses a literal-only AST grammar —
    never ``eval`` — because cells come from PUBLIC markdown
    (reference `setup.py` trusts its own tree; we do not)."""
    expr = expr.strip().strip("`")
    try:
        return _eval_literal(ast.parse(expr, mode="eval"))
    except Exception:
        return expr
