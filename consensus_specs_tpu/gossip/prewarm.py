"""Fork-choice cache pre-warm (closes the ROADMAP cross-block reuse item).

`on_block` replays are the norm, not the exception: sibling blocks at
one slot carry overlapping attestation sets, fork-choice re-applies
gossip aggregates whose committees a just-imported block already
aggregated, and checkpoint re-orgs re-verify whole committee surfaces.
The expensive host-side step is the participant G1 aggregation —
O(committee) point adds per set — and sigpipe's aggregate cache is
already content-addressed (keyed by the participant-pubkey digest), so
a warm entry is correct no matter who computed it.

After a block is accepted into the store, `prewarm_block` pushes every
participant aggregate the block implies into that cache via
`AggregatePubkeyCache.warm_many()` — one batched ops.g1_aggregate
dispatch for all cold sets (counted as `aggregate_cache_prewarms`,
never distorting the hit rate): each attestation's attesting set and
the sync aggregate's participant set.  A later gossip aggregate, a
sibling block, or a fork-choice replay with the same participants then
hits warm regardless of which path first saw the block — even when the
block itself was verified scalar.

Best-effort like all collection: a skipped set is a missed warm-up,
never an error.
"""
from __future__ import annotations

from ..sigpipe.cache import AGGREGATES
from ..sigpipe.metrics import METRICS


def prewarm_block(spec, store, block_root) -> int:
    """Warm the aggregate-pubkey cache with every participant set the
    accepted block at `block_root` implies; returns how many entries
    were actually cold (work done).  All cold sums ride ONE batched
    `warm_many` device dispatch (ops.g1_aggregate) instead of a
    per-committee host add loop."""
    block = store.blocks[block_root]
    state = store.block_states[block_root]
    jobs = []
    for attestation in block.body.attestations:
        try:
            indexed = spec.get_indexed_attestation(state, attestation)
            indices = [int(i) for i in indexed.attesting_indices]
            if not indices:
                continue
            pubkeys = [bytes(state.validators[i].pubkey)
                       for i in indices]
            data = attestation.data
            jobs.append((pubkeys,
                         ("att", int(data.target.epoch),
                          int(getattr(data, "index", 0)))))
        except Exception:
            METRICS.inc("gossip_prewarm_skipped")
    if spec.is_post("altair"):
        try:
            aggregate = block.body.sync_aggregate
            participants = [
                bytes(pk) for pk, bit in zip(
                    state.current_sync_committee.pubkeys,
                    aggregate.sync_committee_bits) if bit]
            if participants:
                epoch = int(spec.get_current_epoch(state))
                period = epoch // int(
                    spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
                jobs.append((participants, ("sync", period)))
        except Exception:
            METRICS.inc("gossip_prewarm_skipped")
    try:
        warmed = AGGREGATES.warm_many(jobs) if jobs else 0
    except Exception:
        # unsupervised dispatch has no fallback: a device failure inside
        # the batched sweep must stay a missed warm-up, not abort the
        # gossip drain that already accepted the block
        METRICS.inc("gossip_prewarm_skipped")
        return 0
    if warmed:
        METRICS.inc("gossip_prewarmed_aggregates", warmed)
    return warmed
