"""Bounded per-topic ingress queues with an explicit overflow policy.

A production node cannot let gossip ingress grow without bound: under a
spam flood (or a slow verification backend) an unbounded queue turns
into unbounded memory growth and unbounded latency — the node falls
minutes behind the chain while faithfully verifying garbage.  The
admission pipeline therefore buffers each topic in a `BoundedQueue`
whose overflow policy is *shed-oldest*: the newest message is always
admitted and the oldest queued message is dropped to make room.

Shed-oldest (not shed-newest) because gossip value decays with age: the
newest attestation is the one the fork choice still wants; an
attestation that sat through `depth` arrivals without being drained is
the one whose slot-clock relevance has already decayed.  Every shed is
loud: an incident-log entry (`gossip.queue.<topic>` / `overflow_shed`)
plus the `gossip_shed{overflow}` labeled counter — bounded ingress that
lies about what it dropped is worse than unbounded ingress.
"""
from __future__ import annotations

from collections import deque

from ..resilience.incidents import INCIDENTS
from ..sigpipe.metrics import METRICS


class BoundedQueue:
    """FIFO of admitted messages for one gossip topic."""

    def __init__(self, topic: str, max_depth: int,
                 metrics=METRICS, incidents=INCIDENTS):
        assert max_depth > 0
        self.topic = topic
        self.max_depth = int(max_depth)
        self._items: deque = deque()
        self._metrics = metrics
        self._incidents = incidents
        self.shed_count = 0

    def push(self, item):
        """Enqueue `item`; returns the message shed to make room (the
        oldest), or None when the queue had capacity."""
        shed = None
        if len(self._items) >= self.max_depth:
            shed = self._items.popleft()
            self.shed_count += 1
            self._metrics.inc_labeled("gossip_shed", "overflow")
            self._incidents.record(
                f"gossip.queue.{self.topic}", "overflow_shed",
                depth=self.max_depth,
                seq=getattr(shed, "seq", None))
        self._items.append(item)
        self._metrics.observe(f"gossip_queue_depth_{self.topic}",
                              len(self._items))
        return shed

    def pop_all(self) -> list:
        """Drain the queue in arrival order."""
        items = list(self._items)
        self._items.clear()
        return items

    def __len__(self) -> int:
        return len(self._items)
