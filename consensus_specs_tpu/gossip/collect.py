"""Per-message signature-set + vote-key collection for the batcher.

Mirrors the philosophy of sigpipe/sets.py at the gossip layer: for each
admitted message, predict the BLS checks its fork-choice handler will
perform and emit them as `SignatureSet`s, plus the (validator, voting
slot) keys the equivocation guard tracks.  Collection is READ-ONLY and
best-effort:

* read-only — the handlers mutate the store (on_attestation inserts
  checkpoint states, on_block inserts blocks); collection must not,
  or the pipeline's store would drift from the sequential oracle's.
  Target checkpoint states already cached on the store are read in
  place; missing ones are computed on a private copy held in the
  flush-local cache, never written back.
* best-effort — any failure (unknown target, malformed indices, a
  pre-assert the handler will raise itself) just skips the set: the
  handler re-raises at its own boundary at delivery time, and the
  verification seam falls back to the scalar backend for any check we
  failed to predict.  Collection can therefore never change a verdict,
  only the dispatch count.

Blocks contribute their PROPOSER signature to the window (predicted
from the parent state — see `_block`) plus the proposer's
(slot -> block) vote key; the rest of a block's signature surface
(randao, in-block operations) stays with the block-level pipeline
(sigpipe.block_scope inside state_transition), which reuses the
window's proposer verdict instead of re-batching it.
"""
from __future__ import annotations

from ..sigpipe.metrics import METRICS
# _set is sigpipe's SignatureSet constructor (byte-normalization in one
# place); sharing it keeps the two collection layers from drifting
from ..sigpipe.sets import _set, indexed_attestation_parts
from ..ssz import hash_tree_root


class Collected:
    """What one gossip message contributes to a flush."""

    __slots__ = ("sets", "votes")

    def __init__(self, sets=(), votes=()):
        self.sets = list(sets)      # SignatureSets to micro-batch
        self.votes = list(votes)    # (kind, validator_index, vote_key,
        #                              content digest, ffg) for the
        #                              guard; ffg is the (source epoch,
        #                              target epoch) pair for
        #                              attestation votes (the surround
        #                              detector's input), None elsewhere


def resolve_target_state(spec, store, target, cache):
    """The state `store_target_checkpoint_state` would use for `target`,
    WITHOUT storing it: the store's cached copy when present, else the
    spec's own pure compute half (`compute_target_checkpoint_state` —
    one derivation, no drift) memoized in the flush-local `cache`."""
    state = store.checkpoint_states.get(target)
    if state is not None:
        return state
    key = (int(target.epoch), bytes(target.root))
    state = cache.get(key)
    if state is not None:
        return state
    state = spec.compute_target_checkpoint_state(store, target)
    cache[key] = state
    return state


def _attestation(spec, store, attestation, cache, origin) -> Collected:
    state = resolve_target_state(spec, store, attestation.data.target,
                                 cache)
    indexed = spec.get_indexed_attestation(state, attestation)
    # the one shared mirror of is_valid_indexed_attestation's derivation
    parts = indexed_attestation_parts(spec, state, indexed)
    if parts is None:
        return Collected()
    indices, pubkeys, root = parts
    data = attestation.data
    data_digest = bytes(hash_tree_root(data))
    sets = [_set(pubkeys, root, attestation.signature, "gossip_attestation",
                 origin,
                 hint=("att", int(data.target.epoch),
                       int(getattr(data, "index", 0))))]
    ffg = (int(data.source.epoch), int(data.target.epoch))
    votes = [("attestation", i, int(data.target.epoch), data_digest, ffg)
             for i in indices]
    return Collected(sets, votes)


def _aggregate(spec, store, signed, cache, origin) -> Collected:
    aggregate_and_proof = signed.message
    aggregate = aggregate_and_proof.aggregate
    inner = _attestation(spec, store, aggregate, cache, origin)
    state = resolve_target_state(spec, store, aggregate.data.target, cache)
    # both envelope checks come from the handler's own derivation
    # helpers (fork_choice.py) — one derivation, no drift
    pubkeys, root, signature = spec.gossip_selection_proof_check(
        state, aggregate_and_proof)
    inner.sets.append(_set(pubkeys, root, signature,
                           "gossip_selection_proof", origin))
    pubkeys, root, signature = spec.gossip_aggregate_and_proof_check(
        state, signed)
    inner.sets.append(_set(pubkeys, root, signature,
                           "gossip_aggregate_and_proof", origin))
    return inner


def _sync_message(spec, store, message, origin) -> Collected:
    state = store.block_states[message.beacon_block_root]
    pubkeys, root, signature = spec.gossip_sync_message_check(
        state, message)
    sets = [_set(pubkeys, root, signature, "gossip_sync_message",
                 origin)]
    votes = [("sync", int(message.validator_index), int(message.slot),
              bytes(message.beacon_block_root), None)]
    return Collected(sets, votes)


def _block(spec, store, signed_block, origin) -> Collected:
    block = signed_block.message
    votes = [("block", int(block.proposer_index), int(block.slot),
              bytes(hash_tree_root(block)), None)]
    # Predict the proposer-signature check (state_transition's
    # verify_block_signature) from the PARENT state, without running
    # process_slots: a validator's pubkey never changes at an existing
    # index, and the signing domain only needs the fork version at the
    # block's epoch (passed explicitly — the at-slot state would read
    # the same field).  Mispredictions — proposer index activated at
    # the epoch boundary, a fork upgrade in the slot gap rotating
    # state.fork — just produce a key no seam ever looks up: the block
    # verifies scalar exactly as before, and its own failed-collection
    # counter says so.
    sets = []
    try:
        state = store.block_states[block.parent_root]
        proposer = state.validators[block.proposer_index]
        domain = spec.get_domain(
            state, spec.DOMAIN_BEACON_PROPOSER,
            spec.compute_epoch_at_slot(block.slot))
        root = spec.compute_signing_root(block, domain)
        sets.append(_set([proposer.pubkey], root, signed_block.signature,
                         "gossip_block_proposer", origin))
    except Exception:
        METRICS.inc("gossip_proposer_predict_skipped")
    return Collected(sets, votes)


def _payload_attestation(spec, store, message, origin) -> Collected:
    pubkeys, root, signature = spec.gossip_payload_attestation_check(
        store, message)
    votes = [("payload_attestation", int(message.validator_index),
              int(message.data.slot),
              bytes(hash_tree_root(message.data)), None)]
    return Collected(
        [_set(pubkeys, root, signature, "gossip_payload_attestation",
              origin)],
        votes)


# speclint: disable=global-mutable-state -- static topic -> collector
# dispatch table, fully populated here and never mutated at run time
_COLLECTORS = {
    "attestation": lambda spec, store, payload, cache, origin:
        _attestation(spec, store, payload, cache, origin),
    "aggregate": _aggregate,
    "sync": lambda spec, store, payload, cache, origin:
        _sync_message(spec, store, payload, origin),
    "block": lambda spec, store, payload, cache, origin:
        _block(spec, store, payload, origin),
    "payload_attestation": lambda spec, store, payload, cache, origin:
        _payload_attestation(spec, store, payload, origin),
}

TOPICS = tuple(_COLLECTORS)


def collect(spec, store, topic, payload, cache, seq) -> Collected:
    """Best-effort collection for one message; failures yield an empty
    Collected (scalar delivery, no guard observation) and a counter."""
    try:
        return _COLLECTORS[topic](spec, store, payload, cache,
                                  (topic, seq))
    except Exception:
        METRICS.inc("gossip_collect_skipped")
        METRICS.inc_labeled("gossip_collect_skipped_by_topic", topic)
        return Collected()
