"""Duplicate suppression and slashable-equivocation quarantine.

Two independent defenses against adversarial gossip:

* `SeenCache` — content-addressed dedup.  Gossip meshes redeliver: the
  same attestation arrives from every peer that relays it.  The cache
  keys messages by hash_tree_root, bounded FIFO (an attacker cannot
  grow it), so a redelivered message costs one dict lookup instead of a
  pairing.  Hits land in `gossip_dedup_hits` (the dedup hit rate is one
  of the headline pipeline metrics).  Digests are recorded when a
  message is actually admitted and *discarded again* when it is shed
  for capacity reasons (queue overflow, quota shed, peer eviction) —
  honest mesh redelivery of a message the node dropped under load must
  get a second chance once load subsides.

* `EquivocationGuard` — slashable-vote detection at the admission edge.
  A validator that signs two DIFFERENT messages for the same voting
  slot (two attestation datas with one target epoch: a double vote;
  two blocks at one slot; two sync votes for one slot) is provably
  equivocating, and so is a validator whose attestation SURROUNDS (or
  is surrounded by) one of its earlier votes — source_1 < source_2 and
  target_2 < target_1, the second half of
  `is_slashable_attestation_data`.  The guard remembers, per verified
  vote, both the first (key -> content digest) entry for double-vote
  detection and a bounded per-validator (source epoch, target epoch)
  FFG history for surround detection — the pipeline records a vote
  only after the carrying message passed signature verification and
  was accepted, and quarantines only when the CONFLICTING message's
  signature verifies too.  Unverified junk claiming a validator index
  can therefore never frame that validator (no censorship vector).  On
  a genuine conflict the validator index is quarantined — its
  sole-signer traffic is shed from then on — and the evidence pair is
  surfaced through the incident log (`gossip.equivocation` /
  `quarantine`, with both digests; surround evidence carries the two
  source->target spans too), which is exactly what a slashing
  inclusion pipeline needs to pick up.

  Decisions are content-addressed and first-verified-write-wins:
  re-seeing the SAME digest is a duplicate, not an equivocation, and
  the decision sequence is a pure function of the (message, verdict)
  sequence — deterministic under replay, which the chaos tier relies
  on.
"""
from __future__ import annotations

from collections import OrderedDict

from ..resilience.incidents import INCIDENTS
from ..sigpipe.metrics import METRICS


class SeenCache:
    def __init__(self, max_size: int = 1 << 16, metrics=METRICS):
        self._seen: OrderedDict = OrderedDict()
        self._max = int(max_size)
        self._metrics = metrics

    def seen_before(self, digest: bytes) -> bool:
        """Dedup check (counted): True for a digest already admitted."""
        if digest in self._seen:
            self._metrics.inc("gossip_dedup_hits")
            return True
        self._metrics.inc("gossip_dedup_misses")
        return False

    def add(self, digest: bytes) -> None:
        if digest in self._seen:
            return
        if len(self._seen) >= self._max:
            self._seen.popitem(last=False)
        self._seen[digest] = True

    def discard(self, digest: bytes) -> None:
        """Forget a digest whose message was shed for capacity reasons:
        redelivery deserves a fresh admission attempt."""
        self._seen.pop(digest, None)

    def __len__(self) -> int:
        return len(self._seen)


class EquivocationGuard:
    # bound on the per-validator FFG history the surround detector
    # scans: weak-subjectivity-period-scale voting is epochs apart, so
    # a recent window is what real surround evidence lands in
    MAX_FFG_VOTES = 64

    def __init__(self, max_keys: int = 1 << 16,
                 metrics=METRICS, incidents=INCIDENTS):
        self._first: OrderedDict = OrderedDict()   # vote key -> digest
        self._ffg: OrderedDict = OrderedDict()     # validator ->
        #                                            [(source, target,
        #                                              digest)]
        self._max = int(max_keys)
        self._metrics = metrics
        self._incidents = incidents
        self.quarantined: set = set()              # validator indices

    def is_quarantined(self, validator_index: int) -> bool:
        return int(validator_index) in self.quarantined

    def first_vote(self, kind: str, validator_index: int, vote_key):
        """The recorded verified digest for this voting key, if any."""
        return self._first.get((kind, int(validator_index), vote_key))

    @staticmethod
    def _surrounds(a, b) -> bool:
        """Does FFG vote `a` (source, target) surround `b`?"""
        return a[0] < b[0] and b[1] < a[1]

    def surround_conflict(self, validator_index: int, ffg):
        """A recorded verified (source, target, digest) vote that `ffg`
        surrounds or is surrounded by, if any — the
        is_slashable_attestation_data surround arm, evaluated against
        this validator's verified history."""
        if ffg is None:
            return None
        history = self._ffg.get(int(validator_index))
        if not history:
            return None
        for recorded in history:
            pair = (recorded[0], recorded[1])
            if self._surrounds(ffg, pair) or self._surrounds(pair, ffg):
                return recorded
        return None

    def _record_ffg(self, validator_index: int, ffg,
                    digest: bytes) -> None:
        history = self._ffg.get(validator_index)
        if history is None:
            if len(self._ffg) >= self._max:
                self._ffg.popitem(last=False)
            history = self._ffg[validator_index] = []
        entry = (ffg[0], ffg[1], digest)
        if entry not in history:
            if len(history) >= self.MAX_FFG_VOTES:
                history.pop(0)
            history.append(entry)

    def observe(self, kind: str, validator_index: int, vote_key,
                digest: bytes, ffg=None) -> bool:
        """Record one VERIFIED (validator, vote).  Returns True when
        consistent (first vote, or a repeat of the same content); on a
        conflict — double vote on the key, or a surround against the
        FFG history when `ffg` is given — the validator is quarantined
        with evidence and False is returned.  Only call this for
        messages whose signatures verified — the pipeline does,
        post-delivery."""
        validator_index = int(validator_index)
        key = (kind, validator_index, vote_key)
        first = self._first.get(key)
        if first is not None and first != digest:
            self.quarantine(kind, validator_index, vote_key, first,
                            digest)
            return False
        if ffg is not None:
            conflict = self.surround_conflict(validator_index, ffg)
            if conflict is not None:
                self.quarantine_surround(validator_index, ffg, digest,
                                         conflict)
                return False
        if first is None:
            if len(self._first) >= self._max:
                self._first.popitem(last=False)
            self._first[key] = digest
        if ffg is not None:
            self._record_ffg(validator_index, ffg, digest)
        return True

    def quarantine(self, kind: str, validator_index: int, vote_key,
                   first: bytes, second: bytes) -> None:
        """Quarantine `validator_index` over a verified conflicting
        vote pair, logging the evidence digests."""
        validator_index = int(validator_index)
        if validator_index in self.quarantined:
            return
        self.quarantined.add(validator_index)
        self._metrics.inc("gossip_equivocations")
        self._incidents.record(
            "gossip.equivocation", "quarantine", kind=kind,
            validator_index=validator_index, vote=repr(vote_key),
            first=first.hex(), second=second.hex())

    def quarantine_surround(self, validator_index: int, ffg,
                            digest: bytes, conflict) -> None:
        """Quarantine `validator_index` over verified surround evidence:
        `conflict` is the recorded (source, target, digest) vote the new
        (ffg, digest) vote surrounds or is surrounded by."""
        validator_index = int(validator_index)
        if validator_index in self.quarantined:
            return
        self.quarantined.add(validator_index)
        self._metrics.inc("gossip_equivocations")
        self._incidents.record(
            "gossip.equivocation", "quarantine", kind="surround",
            validator_index=validator_index,
            first_vote=f"{conflict[0]}->{conflict[1]}",
            second_vote=f"{ffg[0]}->{ffg[1]}",
            first=conflict[2].hex(), second=digest.hex())
