"""The gossip admission pipeline: bounded ingress in front of the
fork-choice handlers.

    pipe = AdmissionPipeline(spec, store, GossipConfig(...), clock)
    pipe.submit("attestation", attestation, peer="16Uiu...")
    ...
    pipe.poll()      # flush when the deadline/size window closes
    pipe.drain()     # force everything through (end of slot, tests)

Message life cycle:

    submit ──dedup──quota──▶ bounded topic queue
                               │ (window: 50 ms / 128 msgs / drain)
    flush: collect sets ──▶ micro-batch verify
                               │
    equivocation gate + deliver in arrival order through the
    fork-choice handlers, batch verdicts installed at the seams;
    verified-and-accepted messages record their votes in the guard

Admission decisions (duplicate, over-quota, overflow-shed, equivocation
quarantine) are made from bounded state and an injected clock — a
seeded schedule replays to the same decisions every run, which is what
lets the chaos tier diff the pipeline against its oracle.

CONCURRENCY.  `submit()` is thread-safe: admission state (seq
allocation, dedup cache, quotas, queues, results) lives under one
ingress lock, and delivery follows a single-drainer discipline — flushes
run only under the drainer lock, `submit`'s closing `poll()` simply
skips when another thread is already draining (that drainer's own
flush/poll loop picks the window up).  Handler execution — the one place
the fork-choice store is touched — is therefore always serialized, so
concurrent ingress can never corrupt queues, quotas, or the store, and
the delivered sequence remains a valid sequential schedule the scalar
oracle can replay.

PIPELINING.  The drainer double-buffers flushes through the async
engine (sigpipe/pipeline_async.py): window N+1 is STAGED (popped,
collected, its batch-verify submitted as a FlushTicket) before window
N is joined and delivered, so handler execution overlaps the next
window's device verify.  Only the verify crosses a thread boundary —
collection and delivery both stay on the drainer, in window order, so
the store is never touched concurrently and the single-drainer
discipline above is unchanged.  With `ASYNC_FLUSH=0` (or a node
context installed — scenario fleets) tickets complete inline and the
flush shape is exactly the historical one.

SEMANTICS CONTRACT.  For the messages the pipeline delivers, per-message
accept/reject verdicts and the resulting store are byte-identical to
applying the same messages one at a time through the bare handlers
(`apply_scalar`): delivery happens in arrival order, the batch verdicts
are content-addressed substitutes consumed at the handlers' own seam
call sites, any un-collected check falls back to the scalar backend,
and collection itself never touches the store.  The pipeline changes
WHICH messages get processed (that's its job: shed the flood) and HOW
MANY dispatches verification costs — never what any processed message
does to the store.
"""
from __future__ import annotations

from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass

from ..sigpipe import scheduler
from ..sigpipe.metrics import METRICS
from ..sigpipe.verify import VerdictMap
from ..ssz import hash_tree_root
from ..utils import nodectx
from ..utils.clock import MONOTONIC
from ..utils.locks import named_lock, named_rlock
from . import collect as _collect
from .batcher import FLUSH_DRAIN, DeadlineBatcher
from .dedup import EquivocationGuard, SeenCache
from .prewarm import prewarm_block
from .queues import BoundedQueue
from .quota import PeerQuotas

TOPICS = _collect.TOPICS

# the exception classes a fork-choice handler uses to reject a message;
# anything else is a bug and propagates (the chaos tier asserts none do
# while the supervisor is armed)
REJECTION_ERRORS = (AssertionError, KeyError, ValueError, IndexError)

# topics whose handler can ACCEPT without having verified the
# signature (eip7732 on_payload_attestation_message no-ops on
# stale-slot messages); their votes need an explicit verification gate
_UNVERIFIED_ACCEPT_TOPICS = frozenset({"payload_attestation"})


@dataclass
class GossipConfig:
    queue_depth: int = 1024         # per-topic ingress bound
    window_s: float = 0.05          # micro-batch deadline
    max_batch: int = 128            # micro-batch size cap
    mode: str = "fused"             # sigpipe scheduler mode
    bucket_capacity: float = 64.0   # per-peer token burst
    refill_rate: float = 16.0       # per-peer tokens/sec
    quota_policy: str = "defer"     # "defer" (backpressure) or "shed"
    max_deferred: int = 256         # per-peer backpressure backlog bound
    max_peers: int = 1024           # peer-table bound (LRU)
    seen_cache_size: int = 1 << 16  # dedup bound
    history_bound: int = 1 << 16    # results / delivered_log retention
    scalar_only: bool = False       # the sequential-oracle mode: same
    #                                 admission, no micro-batching


@dataclass
class Message:
    seq: int
    topic: str
    peer: str
    payload: object
    digest: bytes


@dataclass
class Result:
    seq: int
    topic: str
    peer: str
    status: str                 # queued|deferred|accepted|rejected|shed
    detail: str = ""

    @property
    def final(self) -> bool:
        return self.status in ("accepted", "rejected", "shed")


class AdmissionPipeline:
    def __init__(self, spec, store, config: GossipConfig | None = None,
                 clock=MONOTONIC, *, batcher=None, quotas=None,
                 seen=None, guard=None, transport=None, ctx=None):
        """Every stateful component is injected per-instance (clock,
        batcher, quotas, dedup cache, equivocation guard) so N
        pipelines can coexist in one process without aliasing — the
        scenario harness's per-node instantiation contract.  Pass a
        pre-built component to share state across pipeline lifetimes
        (the driver keeps each node's `guard` across a simulated crash:
        slashing-protection history is durable state, the seen cache is
        not).

        `transport`, when given, is called as ``transport(message)``
        for every ACCEPTED message — the relay seam a mesh simulation
        (or a real gossipsub binding) hangs forwarding on.  `ctx` is a
        `nodectx.NodeContext`; when set, every public entry point runs
        under it so metrics and incidents from this pipeline (and the
        handlers it drives) land in that node's own registries."""
        self.spec = spec
        self.store = store
        self.config = config or GossipConfig()
        self.clock = clock
        self.ctx = ctx
        self.transport = transport
        cfg = self.config
        self.queues = {topic: BoundedQueue(topic, cfg.queue_depth)
                       for topic in TOPICS}
        self.batcher = batcher or DeadlineBatcher(
            cfg.window_s, cfg.max_batch, cfg.mode, clock)
        self.quotas = quotas or PeerQuotas(
            cfg.bucket_capacity, cfg.refill_rate,
            policy=cfg.quota_policy, max_deferred=cfg.max_deferred,
            max_peers=cfg.max_peers, clock=clock)
        # only topics this spec can actually handle: a submit for an
        # unsupported topic must fail THERE, not explode mid-flush and
        # abandon the rest of an already-popped window
        self.topics = tuple(t for t in TOPICS
                            if hasattr(spec, _HANDLER_METHODS[t]))
        self.seen = seen or SeenCache(cfg.seen_cache_size)
        self.guard = guard or EquivocationGuard()
        self.results: dict = {}         # seq -> Result (bounded)
        self.delivered_log = deque(maxlen=cfg.history_bound)
        self._finalized_order: deque = deque()  # eviction order for results
        self._seq = 0
        # ingress lock: admission/bookkeeping state (seq, seen, quotas,
        # queues, batcher window, results).  drainer lock: the
        # single-drainer discipline — whoever holds it owns flushing and
        # handler delivery.  Order: drainer may take ingress, never the
        # reverse.
        self._ingress_lock = named_rlock("gossip.ingress")
        self._drainer_lock = named_lock("gossip.drainer")

    def _scope(self):
        """The node-context region every public entry point runs under
        (no-op without a ctx).  Reentrant, so submit->poll nesting just
        shadows."""
        return nodectx.use(self.ctx) if self.ctx is not None \
            else nullcontext()

    # -- ingress -------------------------------------------------------
    def submit(self, topic: str, payload, peer: str = "local") -> int:
        """Admit one gossip message; returns its sequence number.  May
        trigger a size-cap flush.  The verdict lands in results[seq].
        Thread-safe: admission runs under the ingress lock; the closing
        poll() only flushes when no other thread is already draining."""
        with self._scope():
            return self._submit(topic, payload, peer)

    def _submit(self, topic: str, payload, peer: str) -> int:
        assert topic in self.topics, \
            f"topic {topic!r} not supported by {self.spec.fork} spec"
        digest = bytes(hash_tree_root(payload))     # hash outside locks
        with self._ingress_lock:
            self._seq += 1
            seq = self._seq
            message = Message(seq, topic, peer, payload, digest)
            METRICS.inc_labeled("gossip_submitted", topic)

            if self.seen.seen_before(digest):
                METRICS.inc_labeled("gossip_shed", "duplicate")
                self._finalize(message, "shed", "duplicate")
                return seq

            outcome = self.quotas.admit(peer, message)
            if outcome == "shed":
                # capacity shed: NOT marked seen — redelivery retries
                self._finalize(message, "shed", "quota")
                return seq
            self.seen.add(digest)
            self._shed_evicted_backlogs()
            if outcome == "deferred":
                self.results[seq] = Result(seq, topic, peer, "deferred")
                return seq

            self._enqueue(message)
        self.poll()
        return seq

    def _enqueue(self, message: Message) -> None:
        self.results[message.seq] = Result(
            message.seq, message.topic, message.peer, "queued")
        shed = self.queues[message.topic].push(message)
        if shed is not None:
            self.seen.discard(shed.digest)      # capacity shed: retryable
            self._finalize(shed, "shed", "overflow")
        self.batcher.note_enqueued()

    def _shed_evicted_backlogs(self) -> None:
        """Finalize deferred messages orphaned by peer-table eviction:
        their quota lane is gone, so they shed (retryable — the seen
        cache forgets them)."""
        for orphan in self.quotas.pop_evicted():
            METRICS.inc_labeled("gossip_shed", "quota")
            self.seen.discard(orphan.digest)
            self._finalize(orphan, "shed", "quota_evicted")

    # -- the window ----------------------------------------------------
    def pending_count(self) -> int:
        with self._ingress_lock:
            return sum(len(q) for q in self.queues.values())

    def poll(self) -> bool:
        """Release any quota-deferred messages whose buckets refilled,
        then flush while the batch window is closed (deadline or size);
        returns whether a flush happened.  Releasing here — not just at
        drain — is what makes deferral backpressure rather than
        starvation: the normal submit/poll loop frees the backlog as
        tokens accrue.  Single-drainer: when another thread holds the
        drainer lock this returns immediately — and so that skipped
        poll is never lost, the active drainer re-checks the window
        after RELEASING the lock and resumes if a racing submit filled
        one (a submit's enqueue always happens before its failed
        acquire, so the re-check is ordered after it)."""
        with self._scope():
            return self._poll()

    def _poll(self) -> bool:
        flushed = False
        while True:
            if not self._drainer_lock.acquire(blocking=False):
                return flushed
            try:
                staged_prev = None
                try:
                    while True:
                        with self._ingress_lock:
                            for message in self.quotas.take_refilled():
                                self._enqueue(message)
                            reason = self.batcher.flush_reason(
                                self.pending_count())
                        if reason is None:
                            break
                        # double-buffered flush pipeline: stage window
                        # N+1 (pop + collect + submit its verify to the
                        # async engine) BEFORE joining and delivering
                        # window N, so N's handler execution overlaps
                        # N+1's device verify.  Delivery stays in
                        # window order, so the equivocation gate and
                        # the store see the exact sequential schedule
                        # the scalar oracle replays.
                        staged = self._stage_flush(reason)
                        if staged_prev is not None:
                            prev, staged_prev = staged_prev, staged
                            self._complete_flush(prev)
                        else:
                            staged_prev = staged
                        flushed = True
                    if staged_prev is not None:
                        prev, staged_prev = staged_prev, None
                        self._complete_flush(prev)
                finally:
                    # a non-rejection handler exception while delivering
                    # window N must not silently drop already-popped
                    # window N+1 (the sequential path would have left it
                    # queued): deliver it best-effort; the PRIMARY
                    # exception keeps propagating
                    if staged_prev is not None:
                        self._complete_salvage(staged_prev)
            finally:
                self._drainer_lock.release()
            with self._ingress_lock:
                if self.batcher.flush_reason(self.pending_count()) \
                        is None:
                    return flushed

    def drain(self) -> list:
        """Force every queued and quota-deferred message through;
        returns the finalized Results in seq order.  Deferred messages
        whose buckets are still empty stay deferred (backpressure is
        allowed to outlive a drain)."""
        with self._scope():
            with self._drainer_lock:
                with self._ingress_lock:
                    for message in self.quotas.take_refilled():
                        self._enqueue(message)
                staged_prev = None
                try:
                    while self.pending_count():
                        staged = self._stage_flush(FLUSH_DRAIN)
                        if staged_prev is not None:
                            prev, staged_prev = staged_prev, staged
                            self._complete_flush(prev)
                        else:
                            staged_prev = staged
                    if staged_prev is not None:
                        prev, staged_prev = staged_prev, None
                        self._complete_flush(prev)
                finally:
                    if staged_prev is not None:
                        self._complete_salvage(staged_prev)
            # cover a racing submit whose poll() skipped while we held
            # the drainer lock (same re-check-after-release discipline
            # as poll)
            self._poll()
            return self.verdicts()

    def _flush(self, reason: str) -> None:
        """Verify and deliver one window back-to-back (the unpipelined
        shape — stage + immediate complete)."""
        staged = self._stage_flush(reason)
        if staged is not None:
            self._complete_flush(staged)

    def _stage_flush(self, reason: str):
        """The HOST half of a flush: snapshot the window, collect the
        predicted checks (read-only), and submit the batch-verify to
        the async flush engine.  Caller holds the drainer lock;
        queue/batcher state is snapshotted under the ingress lock, then
        collection runs with ingress open so submitting threads are
        never blocked behind it.  Returns (batch, collected_by_seq,
        ticket) — the staged flush `_complete_flush` joins — or None
        for an empty window.

        A window staged before the PREVIOUS window delivered may
        collect against a store that window is still about to advance;
        any check that mispredicts simply misses the verdict map and
        falls back to scalar at the seam (the content-addressing
        contract), so pipelining can change dispatch counts, never
        verdicts."""
        with self._ingress_lock:
            self.batcher.window_closed(reason)
            batch = sorted(
                (m for q in self.queues.values() for m in q.pop_all()),
                key=lambda m: m.seq)
        if not batch:
            return None

        target_cache: dict = {}
        collected_by_seq: dict = {}
        sets = []
        for message in batch:
            collected = _collect.collect(
                self.spec, self.store, message.topic, message.payload,
                target_cache, message.seq)
            collected_by_seq[message.seq] = collected
            sets.extend(collected.sets)

        # micro-batch them (scalar oracle mode skips)
        ticket = None
        if not self.config.scalar_only:
            # speclint: disable=conc-unguarded-attr -- verify_async only
            # wraps the already-collected sets into a flush submit; it
            # reads none of the batcher's window state (that was closed
            # under the ingress lock above), so holding ingress here
            # would serialize submitters behind the device dispatch
            ticket = self.batcher.verify_async(sets)
        return (batch, collected_by_seq, ticket)

    def _complete_salvage(self, staged) -> None:
        """Deliver a staged window after the PREVIOUS window's delivery
        raised a non-rejection (bug-class) exception: the messages are
        already popped, so dropping them would lose verdicts the
        sequential path would still have produced.  A secondary failure
        here is counted, not raised — the primary exception is the one
        that must surface."""
        try:
            self._complete_flush(staged)
        except Exception:
            METRICS.inc("gossip_salvage_errors")

    def _complete_flush(self, staged) -> None:
        """The JOIN half: block on the window's verify ticket, then
        screen + deliver in arrival order (interleaved, so a conflict
        with an earlier message in the SAME window is caught)."""
        batch, collected_by_seq, ticket = staged
        by_key = ticket.result() if ticket is not None else None
        verdict_map = VerdictMap(by_key) if by_key else None
        for message in batch:
            self._admit_and_deliver(message, collected_by_seq[message.seq],
                                    by_key, verdict_map)

    # -- the equivocation gate -----------------------------------------
    def _sets_verify(self, sets, by_key) -> bool:
        """Do this message's predicted signature checks ALL verify?
        Uses the batch verdicts when available, the scheduler otherwise
        (conflicts are rare, so the extra dispatch is cheap).  Empty
        collection means we cannot vouch — False."""
        if not sets:
            return False
        for s in sets:
            verdict = by_key.get(s.key()) if by_key else None
            if verdict is None:
                verdict = all(scheduler.verify_sets(
                    [s], mode=self.config.mode))
            if not verdict:
                return False
        return True

    def _admit_and_deliver(self, message: Message, collected, by_key,
                           verdict_map) -> None:
        """Quarantine/equivocation gate, then delivery.  Votes are
        recorded only from ACCEPTED (signature-verified) messages, and a
        conflicting message sheds pre-delivery only when its OWN
        signature verifies — unverified junk can neither frame a
        validator nor count as evidence.  Multi-signer aggregates are
        never shed here: one equivocator must not censor a committee."""
        votes = collected.votes
        sole = votes[0] if len(votes) == 1 else None
        # blocks are EXEMPT from the pre-delivery gate: a valid proposal
        # from a locally-quarantined (attestation-equivocating) validator
        # is still canonical for the rest of the network — refusing it
        # would fork this node off the chain.  Proposer equivocation is
        # still detected post-acceptance (observe() below quarantines
        # with evidence); only non-block traffic is shed.
        if sole is not None and message.topic != "block":
            kind, validator_index, vote_key, digest, ffg = sole
            if self.guard.is_quarantined(validator_index):
                METRICS.inc_labeled("gossip_shed", "quarantined")
                self._finalize(message, "shed", "quarantined")
                return
            first = self.guard.first_vote(kind, validator_index,
                                          vote_key)
            if (first is not None and first != digest
                    and self._sets_verify(collected.sets, by_key)):
                self.guard.quarantine(kind, validator_index, vote_key,
                                      first, digest)
                METRICS.inc_labeled("gossip_shed", "equivocation")
                self._finalize(message, "shed", "equivocation")
                return
            # surround arm: an FFG vote that surrounds (or is
            # surrounded by) one of this validator's VERIFIED earlier
            # votes sheds pre-delivery iff its own signature verifies —
            # the same no-framing discipline as the double-vote gate
            surround = self.guard.surround_conflict(validator_index,
                                                    ffg)
            if (surround is not None
                    and self._sets_verify(collected.sets, by_key)):
                self.guard.quarantine_surround(validator_index, ffg,
                                               digest, surround)
                METRICS.inc_labeled("gossip_shed", "equivocation")
                self._finalize(message, "shed", "surround")
                return
        accepted = self._deliver(message, verdict_map)
        if accepted and votes:
            # every handler proves the signature as part of acceptance
            # EXCEPT eip7732's PTC handler, which no-op-accepts
            # stale-slot messages unverified — for that topic a vote is
            # recorded only when the predicted checks verified, so junk
            # can never frame a validator through the ignore path
            if (message.topic not in _UNVERIFIED_ACCEPT_TOPICS
                    or self._sets_verify(collected.sets, by_key)):
                for kind, validator_index, vote_key, digest, ffg in votes:
                    self.guard.observe(kind, validator_index, vote_key,
                                       digest, ffg)

    # -- delivery ------------------------------------------------------
    def _deliver(self, message: Message, verdict_map) -> bool:
        self.delivered_log.append((message.seq, message.topic,
                                   message.payload))
        # blocks consume the window map too: the collector predicts the
        # proposer signature, state_transition's verify_block_signature
        # consumes its verdict at the bls_verify seam, and sigpipe's
        # block scope (when enabled) REUSES it rather than re-batching
        # (verify.compute_verdicts lifts outer-map verdicts).  Every
        # other in-block check either rides the block scope or falls
        # back scalar at the seam — content addressing makes a stale
        # or mispredicted key simply invisible.
        use_map = verdict_map is not None
        if use_map:
            with self.spec.install_sigpipe_verdicts(verdict_map):
                accepted, detail = apply_scalar(
                    self.spec, self.store, message.topic, message.payload)
        else:
            accepted, detail = apply_scalar(
                self.spec, self.store, message.topic, message.payload)
        if accepted:
            METRICS.inc_labeled("gossip_accepted", message.topic)
            self._finalize(message, "accepted")
            if message.topic == "block":
                prewarm_block(self.spec, self.store,
                              hash_tree_root(message.payload.message))
            if self.transport is not None:
                # the relay seam: a validated message is what a mesh
                # forwards.  Called after finalize so a forwarding
                # simulation observing results sees this message done.
                self.transport(message)
        else:
            METRICS.inc_labeled("gossip_rejected", message.topic)
            # rejections are often TRANSIENT (attestation a slot early,
            # target block not yet imported — the p2p spec's IGNORE
            # class): forget the digest so honest redelivery revalidates
            # once the condition clears, instead of dying as 'duplicate'.
            # The seen cache is admission state — mutate it under the
            # ingress lock even from the drainer's delivery loop
            with self._ingress_lock:
                self.seen.discard(message.digest)
            self._finalize(message, "rejected", detail)
        return accepted

    def _finalize(self, message: Message, status: str,
                  detail: str = "") -> None:
        # called from both submit threads (ingress lock held) and the
        # drainer's delivery loop (ingress open) — take it reentrantly
        with self._ingress_lock:
            self.results[message.seq] = Result(
                message.seq, message.topic, message.peer, status, detail)
            # O(1) amortized pruning: finalized verdicts evict
            # oldest-first once over the bound.  The bound counts
            # FINALIZED entries only — in-flight (queued/deferred)
            # entries are never evicted and must not displace fresh
            # verdicts either, or a large deferred backlog would evict
            # every new verdict the moment it lands
            self._finalized_order.append(message.seq)
            while len(self._finalized_order) > self.config.history_bound:
                seq = self._finalized_order.popleft()
                if self.results.get(seq) is not None and \
                        self.results[seq].final:
                    del self.results[seq]

    def verdicts(self) -> list:
        """Every finalized Result in arrival order."""
        with self._ingress_lock:
            return [self.results[seq] for seq in sorted(self.results)
                    if self.results[seq].final]


# speclint: disable=global-mutable-state -- static topic -> handler-name
# table, fully populated here and never mutated at run time
_HANDLER_METHODS = {
    "attestation": "on_attestation",
    "aggregate": "on_aggregate_and_proof",
    "sync": "on_sync_committee_message",
    "block": "on_block",
    "payload_attestation": "on_payload_attestation_message",
}

# speclint: disable=global-mutable-state -- static topic -> scalar-apply
# table, fully populated here and never mutated at run time
_HANDLERS = {
    "attestation": lambda spec, store, payload:
        spec.on_attestation(store, payload, is_from_block=False),
    "aggregate": lambda spec, store, payload:
        spec.on_aggregate_and_proof(store, payload),
    "sync": lambda spec, store, payload:
        spec.on_sync_committee_message(store, payload),
    "block": lambda spec, store, payload:
        spec.on_block(store, payload),
    "payload_attestation": lambda spec, store, payload:
        spec.on_payload_attestation_message(store, payload),
}


def apply_scalar(spec, store, topic, payload):
    """THE per-message oracle: apply one gossip message through its bare
    fork-choice handler; returns (accepted, rejection detail).  The
    pipeline's delivery loop calls exactly this (with batch verdicts
    installed at the seams), so pipeline and oracle share one handler
    table and one rejection-exception contract by construction."""
    try:
        _HANDLERS[topic](spec, store, payload)
    except REJECTION_ERRORS as e:
        return False, f"{type(e).__name__}: {e}"
    return True, ""


def store_fingerprint(spec, store) -> dict:
    """JSON-able digest of the observable fork-choice store state — what
    the parity tests compare between the pipeline and the sequential
    oracle."""
    head = spec.get_head(store)
    head = getattr(head, "root", head)
    checkpoint = lambda c: (int(c.epoch), bytes(c.root).hex())  # noqa: E731
    return {
        "time": int(store.time),
        "head": bytes(head).hex(),
        "blocks": sorted(bytes(r).hex() for r in store.blocks),
        "justified": checkpoint(store.justified_checkpoint),
        "finalized": checkpoint(store.finalized_checkpoint),
        "unrealized_justified":
            checkpoint(store.unrealized_justified_checkpoint),
        "proposer_boost_root": bytes(store.proposer_boost_root).hex(),
        "checkpoint_states": sorted(
            checkpoint(c) for c in store.checkpoint_states),
        "latest_messages": {
            int(i): (int(getattr(m, "epoch", getattr(m, "slot", 0))),
                     bytes(m.root).hex())
            for i, m in store.latest_messages.items()},
        "equivocating_indices": sorted(
            int(i) for i in store.equivocating_indices),
    }
