"""Gossip admission pipeline for the fork-choice hot path.

A production node serving millions of validators lives or dies on how
its network-facing validation layer behaves under overload and
adversarial input.  This package puts a bounded, observable, batching
admission pipeline in front of the fork-choice handlers:

* queues.py   — bounded per-topic ingress (shed-oldest, incident-logged)
* batcher.py  — deadline/size micro-batcher: one fused signature
                dispatch per window through sigpipe.scheduler, bisection
                isolating bad messages, breaker-aware scalar fallback
* quota.py    — per-peer token buckets with defer/shed backpressure
* dedup.py    — content-addressed duplicate suppression + slashable
                equivocation quarantine with logged evidence
* collect.py  — read-only best-effort SignatureSet prediction per topic
* prewarm.py  — on_block pre-warm of sigpipe's aggregate-pubkey cache
                (cross-block fork-choice reuse)
* pipeline.py — AdmissionPipeline tying it together, plus the
                `apply_scalar` sequential oracle and store_fingerprint

Semantics contract (pipeline.py docstring): delivered messages behave
byte-identically to the scalar per-message path; the pipeline only
decides what to shed and how few dispatches verification costs.
"""
from .batcher import DeadlineBatcher
from .dedup import EquivocationGuard, SeenCache
from .pipeline import (
    TOPICS, AdmissionPipeline, GossipConfig, Result, apply_scalar,
    store_fingerprint,
)
from .prewarm import prewarm_block
from .queues import BoundedQueue
from .quota import PeerQuotas, TokenBucket
from ..utils.clock import ManualClock, SystemClock

__all__ = [
    "AdmissionPipeline", "BoundedQueue", "DeadlineBatcher",
    "EquivocationGuard", "GossipConfig", "ManualClock", "PeerQuotas",
    "Result", "SeenCache", "SystemClock", "TOPICS", "TokenBucket",
    "apply_scalar", "prewarm_block", "store_fingerprint",
]
