"""Deadline-driven signature micro-batcher.

Gossip signature checks arrive one at a time but verify cheapest
together: the fused pairing-product dispatch (sigpipe/scheduler.py)
costs nearly the same for 1 set as for 128.  The batcher holds the
window open until either the deadline (default 50 ms) or the size cap
(default 128 messages) is hit — whichever first — then verifies every
collected set as ONE batch and hands back content-keyed verdicts for
the delivery loop's verification seams.

Degradation ladder (every rung keeps verdicts byte-identical, because
the seams fall back to the scalar backend for any check without a batch
verdict):

1. occupancy 1 — a lone message gains nothing from batching; skip the
   dispatch entirely (`gossip_batch_scalar{single_message}`).
2. breaker open / forced scalar at the `gossip.batch_verify` site —
   `resilience.dispatch` routes to the fallback, which simply declines
   to produce batch verdicts (`gossip_batch_scalar{degraded}`); the
   supervisor's own `scalar_fallbacks{breaker_open,...}` counters say
   why.  Fault injection targets this site like any other seam.
3. any unexpected batch error without a supervisor — caught here,
   counted (`gossip_batch_errors`), scalar delivery.

Inside the batch, an invalid message cannot poison its neighbors: the
scheduler's bisection fallback isolates the failing sets, so the rest
of the window still gets its fused verdicts.

Time comes from the injected clock (utils/clock.py) — deadline
decisions replay deterministically from a seeded schedule.
"""
from __future__ import annotations

from ..resilience.supervisor import dispatch
from ..sigpipe import pipeline_async
from ..sigpipe.metrics import METRICS
from ..sigpipe.verify import _batch_verify_unique

FLUSH_DEADLINE = "deadline"
FLUSH_SIZE = "size"
FLUSH_DRAIN = "drain"


class DeadlineBatcher:
    def __init__(self, window_s: float = 0.05, max_batch: int = 128,
                 mode: str = "fused", clock=None, metrics=METRICS):
        self.window_s = float(window_s)
        self.max_batch = int(max_batch)
        self.mode = mode
        self._clock = clock
        self._metrics = metrics
        self._window_started: float | None = None

    # -- window bookkeeping -------------------------------------------
    def note_enqueued(self) -> None:
        if self._window_started is None:
            self._window_started = self._clock.now()

    def flush_reason(self, pending_count: int) -> str | None:
        """Why the window should flush now, or None to keep collecting."""
        if pending_count <= 0:
            return None
        if pending_count >= self.max_batch:
            return FLUSH_SIZE
        if (self._window_started is not None
                and self._clock.now() - self._window_started
                >= self.window_s):
            return FLUSH_DEADLINE
        return None

    def window_closed(self, reason: str) -> None:
        self._window_started = None
        self._metrics.inc_labeled("gossip_window_flushes", reason)

    # -- the batch dispatch -------------------------------------------
    def verify(self, sets):
        """Content-keyed verdicts {set.key(): bool} for `sets`, or None
        when the window is delivered scalar (single message, breaker
        open, or batch failure)."""
        unique_keys = {s.key() for s in sets}
        if not unique_keys:
            return {}
        if len(unique_keys) == 1:
            self._metrics.inc_labeled("gossip_batch_scalar",
                                      "single_message")
            return None

        def device():
            # sigpipe's shared dedup+verify helper (counts dedup_saved);
            # the keyed-dict payload shape also keeps the fault
            # injector's "corrupt" flip (bare bool/list payloads) at the
            # bls seams, where the differential guard defends
            return _batch_verify_unique(sets, mode=self.mode)

        def degraded():
            self._metrics.inc_labeled("gossip_batch_scalar", "degraded")
            return None

        try:
            return dispatch("gossip.batch_verify", device, degraded)
        except Exception:
            # no supervisor installed: degrade here instead
            self._metrics.inc("gossip_batch_errors")
            return degraded()

    def verify_async(self, sets):
        """Submit this window's batch-verify to the async flush engine;
        returns the :class:`pipeline_async.FlushTicket` the delivery
        loop joins on (`result()` is exactly `verify(sets)`'s value).
        The degradation ladder is unchanged — every rung runs on the
        worker and lands in the ticket; with the engine off the ticket
        completes inline before returning."""
        return pipeline_async.submit(
            lambda: self.verify(sets), "gossip_window")
