"""Per-peer token-bucket quotas with backpressure.

Fairness is the point: one spamming peer must degrade only its own
throughput, never the node's.  Each peer gets a token bucket
(`capacity` burst, `refill_rate` tokens/sec, refilled lazily from the
injected clock — utils/clock.py, so seeded schedules replay exactly);
each submitted message costs one token.  An over-quota message is
*deferred* (parked on the peer's bounded backlog and retried when the
bucket refills — backpressure) or *shed* outright under the "shed"
policy; both outcomes are recorded in the incident log and the
`gossip_shed`/`gossip_quota_deferred` counters, so a quota decision is
always reconstructable from the audit trail.

The peer table itself is bounded (LRU over `max_peers`): an attacker
who invents a new peer identity per message must not grow node memory —
evicted peers simply start over with a fresh (full) bucket, which costs
the attacker more than it costs us.  An evicted peer's deferred backlog
is handed back to the pipeline (`pop_evicted()`) to be finalized as
shed, with a `peer_evicted` incident — never silently dropped.
"""
from __future__ import annotations

from collections import OrderedDict, deque

from ..resilience.incidents import INCIDENTS
from ..sigpipe.metrics import METRICS
from ..utils.clock import MONOTONIC


class TokenBucket:
    def __init__(self, capacity: float, refill_rate: float, clock):
        self.capacity = float(capacity)
        self.refill_rate = float(refill_rate)
        self._clock = clock
        self._tokens = float(capacity)
        self._updated = clock.now()

    def _refill(self) -> None:
        now = self._clock.now()
        self._tokens = min(self.capacity,
                           self._tokens
                           + (now - self._updated) * self.refill_rate)
        self._updated = now

    def take(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def tokens(self) -> float:
        self._refill()
        return self._tokens


class PeerQuotas:
    """One bucket per peer, plus the per-peer deferred backlog."""

    def __init__(self, capacity: float, refill_rate: float,
                 policy: str = "defer", max_deferred: int = 256,
                 max_peers: int = 1024, clock=MONOTONIC,
                 metrics=METRICS, incidents=INCIDENTS):
        assert policy in ("defer", "shed")
        self.capacity = capacity
        self.refill_rate = refill_rate
        self.policy = policy
        self.max_deferred = int(max_deferred)
        self.max_peers = int(max_peers)
        self._clock = clock
        self._metrics = metrics
        self._incidents = incidents
        self._buckets: OrderedDict = OrderedDict()
        self._deferred: dict = {}       # peer -> deque of messages
        self._evicted_backlog: list = []    # messages orphaned by LRU
        # earliest instant any deferred peer can afford a token: the
        # per-submit refill poll is O(1) until then, so attacker-parked
        # backlogs cannot tax every later message's admission
        self._next_refill = float("inf")

    def _bucket(self, peer: str) -> TokenBucket:
        bucket = self._buckets.get(peer)
        if bucket is None:
            while len(self._buckets) >= self.max_peers:
                evicted, _ = self._buckets.popitem(last=False)
                orphaned = self._deferred.pop(evicted, ())
                if orphaned:
                    self._evicted_backlog.extend(orphaned)
                    self._incidents.record(
                        "gossip.quota", "peer_evicted", peer=evicted,
                        dropped=len(orphaned))
            bucket = self._buckets[peer] = TokenBucket(
                self.capacity, self.refill_rate, self._clock)
        else:
            self._buckets.move_to_end(peer)
        return bucket

    def pop_evicted(self) -> list:
        """Deferred messages orphaned by peer-table eviction since the
        last call; the pipeline finalizes them as shed."""
        orphaned, self._evicted_backlog = self._evicted_backlog, []
        return orphaned

    def admit(self, peer: str, message) -> str:
        """Charge one token for `message`; returns "ok", "deferred", or
        "shed".  Deferred messages are held on the peer's backlog and
        come back via take_refilled() once tokens exist again."""
        if self._bucket(peer).take(1.0):
            return "ok"
        # unlabeled on purpose: a per-peer label would key a metrics
        # series by attacker-controlled identity (unbounded growth);
        # the bounded incident log carries the peer attribution
        self._metrics.inc("gossip_quota_rejections")
        if self.policy == "defer":
            backlog = self._deferred.setdefault(peer, deque())
            if len(backlog) < self.max_deferred:
                backlog.append(message)
                self._next_refill = min(self._next_refill,
                                        self._token_eta(peer))
                self._metrics.inc("gossip_quota_deferred")
                self._incidents.record(
                    "gossip.quota", "quota_deferred", peer=peer,
                    seq=getattr(message, "seq", None))
                return "deferred"
            # backlog full: the slow lane is saturated too — shed
        self._metrics.inc_labeled("gossip_shed", "quota")
        self._incidents.record(
            "gossip.quota", "quota_shed", peer=peer,
            seq=getattr(message, "seq", None))
        return "shed"

    def _token_eta(self, peer: str) -> float:
        """When `peer`'s bucket can next afford one token."""
        bucket = self._buckets.get(peer)
        if bucket is None or self.refill_rate <= 0:
            return float("inf")
        deficit = max(0.0, 1.0 - bucket.tokens())
        return self._clock.now() + deficit / self.refill_rate

    def take_refilled(self) -> list:
        """Deferred messages whose peers have tokens again, charged and
        released in original arrival (seq) order across peers.  O(1)
        until the earliest bucket can actually afford a token.  Reads
        buckets WITHOUT refreshing the LRU: a refill poll is
        bookkeeping, not peer activity — only real submissions keep a
        peer warm in the table."""
        if not self._deferred or self._clock.now() < self._next_refill:
            return []
        released = []
        for peer in list(self._deferred):
            bucket = self._buckets.get(peer)
            if bucket is None:
                continue    # eviction orphans the backlog with it
            backlog = self._deferred[peer]
            while backlog and bucket.take(1.0):
                released.append(backlog.popleft())
            if not backlog:
                del self._deferred[peer]
        self._next_refill = min(
            (self._token_eta(peer) for peer in self._deferred),
            default=float("inf"))
        released.sort(key=lambda m: getattr(m, "seq", 0))
        return released

    def deferred_count(self) -> int:
        return sum(len(q) for q in self._deferred.values())
