"""Test-vector generator runner.

Counterpart of the reference's gen_helpers/gen_base/gen_runner.py: writes
each case to <output>/<preset>/<fork>/<runner>/<handler>/<suite>/<case>/
as meta.yaml + *.yaml + *.ssz_snappy, with the same reliability contract:

- an INCOMPLETE tag file marks in-progress case dirs; crashes leave it
  behind for `detect_incomplete` to find
- re-runs skip completed case dirs (resumable generation) unless --force
- failures append tracebacks to testgen_error_log.txt and don't abort the
  whole run
- per-runner diagnostics.json with case counts and slow-case durations

Host-level fan-out (the reference's pathos pool / `make -j gen_all`) is
round-robin case sharding: run N processes with `--shard i/N` each
(scripts/gen_vectors.py); resume semantics make the union safe.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
import traceback

import yaml

from . import snappy
from .typing import TestCase, TestProvider
from .vector_test import SkippedTest, run_yields

INCOMPLETE_TAG = "INCOMPLETE"
SLOW_CASE_SECONDS = 1.0


# ---------------------------------------------------------------------------
# yaml conventions: hex-bytes as 0x strings, big ints as decimal strings
# ---------------------------------------------------------------------------

class _VectorDumper(yaml.SafeDumper):
    pass


_VectorDumper.add_representer(
    bytes, lambda d, v: d.represent_scalar(
        "tag:yaml.org,2002:str", "0x" + v.hex()))


def _dump_yaml(obj, path: str) -> None:
    with open(path, "w") as f:
        yaml.dump(obj, f, Dumper=_VectorDumper, default_flow_style=None,
                  sort_keys=False)


# ---------------------------------------------------------------------------
# per-case execution
# ---------------------------------------------------------------------------

def _write_case(case: TestCase, case_dir: str) -> dict:
    """Run one case fn and write its artifacts. Returns diagnostics."""
    os.makedirs(case_dir, exist_ok=True)
    tag_path = os.path.join(case_dir, INCOMPLETE_TAG)
    with open(tag_path, "w"):
        pass

    t0 = time.perf_counter()
    parts = run_yields(case.case_fn)
    meta = {}
    written = 0
    for name, kind, value in parts:
        if kind == "none":
            continue  # expected-invalid marker: simply absent on disk
        if kind == "meta":
            meta[name] = value
        elif kind in ("cfg", "data"):
            _dump_yaml(value, os.path.join(case_dir, f"{name}.yaml"))
            written += 1
        elif kind == "ssz":
            with open(os.path.join(case_dir, f"{name}.ssz_snappy"),
                      "wb") as f:
                f.write(snappy.compress(value))
            written += 1
        else:
            raise ValueError(f"unknown artifact kind {kind!r}")
    if meta:
        _dump_yaml(meta, os.path.join(case_dir, "meta.yaml"))
        written += 1
    elapsed = time.perf_counter() - t0

    os.remove(tag_path)
    return {"files": written, "seconds": elapsed}


def _case_done(case_dir: str) -> bool:
    return (os.path.isdir(case_dir)
            and os.listdir(case_dir)
            and not os.path.exists(os.path.join(case_dir, INCOMPLETE_TAG)))


# ---------------------------------------------------------------------------
# runner entry
# ---------------------------------------------------------------------------

def run_generator(runner_name: str, providers, args=None) -> dict:
    """Generate all cases from `providers` under an output directory.

    Returns the diagnostics dict (also written to diagnostics.json).
    """
    parser = argparse.ArgumentParser(prog=f"gen-{runner_name}")
    parser.add_argument("-o", "--output-dir", required=True)
    parser.add_argument("-f", "--force", action="store_true",
                        help="regenerate existing (complete) case dirs")
    parser.add_argument("--preset-list", nargs="*", default=None)
    parser.add_argument("--fork-list", nargs="*", default=None)
    parser.add_argument("--modcheck", action="store_true",
                        help="only check providers are importable, no output")
    ns = parser.parse_args(args)

    if ns.modcheck:
        for provider in providers:
            provider.prepare()
        return {"modcheck": "ok"}

    diagnostics = {
        "generated": 0, "skipped": 0, "failed": 0,
        "durations": {}, "slow": [],
    }
    error_log = os.path.join(ns.output_dir, "testgen_error_log.txt")
    os.makedirs(ns.output_dir, exist_ok=True)

    for provider in providers:
        provider.prepare()
        for case in provider.make_cases():
            if ns.preset_list and case.preset_name not in ns.preset_list:
                continue
            if ns.fork_list and case.fork_name not in ns.fork_list:
                continue
            case_dir = os.path.join(ns.output_dir, case.dir_path())
            if _case_done(case_dir) and not ns.force:
                diagnostics["skipped"] += 1
                continue
            if os.path.isdir(case_dir):
                shutil.rmtree(case_dir)  # incomplete or forced: regenerate
            try:
                result = _write_case(case, case_dir)
            except SkippedTest:
                # inapplicable under this (fork, preset): no case dir,
                # no error-log entry — mirror the reference's skip path
                shutil.rmtree(case_dir, ignore_errors=True)
                diagnostics["skipped"] += 1
                continue
            except Exception:
                diagnostics["failed"] += 1
                with open(error_log, "a") as f:
                    f.write(f"=== {case.dir_path()} ===\n")
                    f.write(traceback.format_exc() + "\n")
                continue
            diagnostics["generated"] += 1
            diagnostics["durations"][case.dir_path()] = \
                round(result["seconds"], 4)
            if result["seconds"] > SLOW_CASE_SECONDS:
                diagnostics["slow"].append(case.dir_path())
                print(f"(!) slow case {case.dir_path()}: "
                      f"{result['seconds']:.2f}s", file=sys.stderr)

    with open(os.path.join(ns.output_dir,
                           f"diagnostics_{runner_name}.json"), "w") as f:
        json.dump(diagnostics, f, indent=2, sort_keys=True)
    return diagnostics


def detect_incomplete(output_dir: str) -> list:
    """Find case dirs left INCOMPLETE by a crashed run (make detect_errors)."""
    out = []
    for root, _dirs, files in os.walk(output_dir):
        if INCOMPLETE_TAG in files:
            out.append(os.path.relpath(root, output_dir))
    return sorted(out)
