"""Test-vector generator core types.

Counterpart of the reference's gen_helpers/gen_base/gen_typing.py: a
TestCase names its output path (preset/fork/runner/handler/suite/case) and
carries a case function; a TestProvider yields cases for one runner.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterable


def hex_str(b: bytes) -> str:
    """Vector-file hex convention: 0x-prefixed lowercase."""
    return "0x" + bytes(b).hex()


@dataclass
class TestCase:
    fork_name: str
    preset_name: str
    runner_name: str
    handler_name: str
    suite_name: str
    case_name: str
    case_fn: Callable[[], Iterable]   # yields (name, kind, value) parts

    def dir_path(self) -> str:
        return "/".join([self.preset_name, self.fork_name, self.runner_name,
                         self.handler_name, self.suite_name, self.case_name])


@dataclass
class TestProvider:
    prepare: Callable[[], None] = lambda: None
    make_cases: Callable[[], Iterable[TestCase]] = lambda: ()
