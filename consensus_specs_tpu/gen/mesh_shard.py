"""Device-mesh sharded vector generation.

SURVEY §2.6: the reference fans vector generation across workers with
pathos pools and `make -j` across hosts.  The TPU-native equivalent
treats the device mesh as the scheduling substrate: the round-robin
case→worker assignment (the same contract as
`scripts/gen_vectors.py --shard I/N`) is computed ON the mesh with a
shard_map iota — each device lane emits the case indices congruent to
its mesh position — and the host materializes one output shard per
device.  The shards are disjoint and their on-disk union is
byte-identical to the serial run (the INCOMPLETE-tag/resume semantics
of gen.runner make the union safe, exactly as for the process
fan-out).
"""
from __future__ import annotations

import numpy as np

from .runner import run_generator
from .typing import TestProvider


def mesh_case_assignment(mesh, n_cases: int) -> list[list[int]]:
    """Per-device case-index lists, computed by the mesh itself.

    Device d's lane writes indices d, d+n_dev, 2n_dev+d, ... — the
    ``--shard d/n_dev`` round-robin contract — via a shard_map iota, so
    the scheduling artifact executes on the mesh rather than being host
    arithmetic."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..parallel.collectives import AXIS
    from ..parallel.mesh import shard_map

    n_dev = int(np.prod(list(mesh.shape.values())))
    per = -(-n_cases // n_dev) if n_cases else 0
    if per == 0:
        return [[] for _ in range(n_dev)]

    def body():
        d = jax.lax.axis_index(AXIS)
        idx = d + jnp.arange(per, dtype=jnp.int32) * n_dev
        return jnp.where(idx < n_cases, idx, -1)[None]

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                           out_specs=P(AXIS, None)))
    rows = np.asarray(jax.device_get(fn()))
    return [[int(i) for i in row if i >= 0] for row in rows]


def shard_providers(providers, i0: int, n: int):
    """THE round-robin shard filter (the ``--shard i0/n`` contract):
    within each provider's enumeration order, keep cases whose index is
    ≡ i0 (mod n).  scripts/gen_vectors.py and the mesh fan-out both use
    this one implementation, so host-level and device-level sharding
    compose without drift."""
    out = []
    for provider in providers:
        def make_cases(p=provider):
            for idx, case in enumerate(p.make_cases()):
                if idx % n == i0:
                    yield case
        out.append(TestProvider(prepare=provider.prepare,
                                make_cases=make_cases))
    return out


def count_cases(providers_fn) -> int:
    n = 0
    for provider in providers_fn():
        provider.prepare()
        n += sum(1 for _ in provider.make_cases())
    return n


def run_generator_mesh_sharded(runner_name: str, providers_fn, out_dir,
                               mesh, extra_args=()) -> dict:
    """Generate a runner's cases as one shard per mesh device and merge
    the diagnostics (written back over the per-shard diagnostics file,
    which each run_generator call rewrites).  Residue d of the
    round-robin belongs to mesh device d — mesh_case_assignment is the
    executable statement of that ownership.  `providers_fn` is called
    once per shard; each shard walks the (deterministic) enumeration
    and keeps its residue class, the same cost shape as the process
    fan-out."""
    import json
    import os

    n_dev = int(np.prod(list(mesh.shape.values())))
    merged = {"generated": 0, "skipped": 0, "failed": 0,
              "shards": n_dev, "durations": {}, "slow": []}
    for dev in range(n_dev):
        shard = shard_providers(providers_fn(), dev, n_dev)
        diag = run_generator(
            runner_name, shard,
            args=["-o", str(out_dir), *extra_args])
        for key in ("generated", "skipped", "failed"):
            merged[key] += diag.get(key, 0)
        merged["durations"].update(diag.get("durations", {}))
        merged["slow"].extend(diag.get("slow", []))
    # the last shard's run_generator left only ITS diagnostics on disk;
    # replace with the merged view so failures in any shard are visible
    diag_path = os.path.join(str(out_dir),
                             f"diagnostics_{runner_name}.json")
    if os.path.exists(diag_path):
        with open(diag_path, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
    return merged
