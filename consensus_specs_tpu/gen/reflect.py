"""Reflect decorated spec tests into vector-generator cases.

Counterpart of the reference's gen_from_tests machinery
(/root/reference/tests/core/pyspec/eth2spec/gen_helpers/gen_from_tests/
gen.py:18-61,101-116,140-203): every `@spec_state_test`-style function in a
module IS a vector case — `generate_from_tests` walks a module's test
functions and collects their `make_vector_cases` output, so pytest suites
and conformance vectors are one codebase.
"""
from __future__ import annotations

import importlib
import inspect

from .typing import TestCase, TestProvider


def generate_from_tests(runner_name: str, handler_name: str, module,
                        forks=None, presets=None, suite_name="pyspec"):
    """TestCases for every decorated test_* function in `module`."""
    if isinstance(module, str):
        module = importlib.import_module(module)
    cases: list[TestCase] = []
    for name, fn in inspect.getmembers(module):
        if not name.startswith("test_"):
            continue
        maker = getattr(fn, "make_vector_cases", None)
        if maker is None:
            continue  # plain unit test, not exported (reference check_mods)
        cases.extend(maker(runner_name, handler_name, suite_name=suite_name,
                           forks=forks, presets=presets))
    return cases


def providers_from_handlers(runner_name: str, handler_modules: dict,
                            forks=None, presets=None):
    """One TestProvider covering {handler_name: module(s)} — the shape of a
    runner main (reference run_state_test_generators)."""
    def make_cases():
        for handler, mods in handler_modules.items():
            if not isinstance(mods, (list, tuple)):
                mods = [mods]
            for mod in mods:
                yield from generate_from_tests(
                    runner_name, handler, mod, forks=forks, presets=presets)
    return [TestProvider(make_cases=make_cases)]


def check_handler_modules(handler_modules: dict) -> list:
    """Completeness check: every named module imports and contains at
    least one exportable test (reference check_mods gen.py:140-203).
    Returns a list of problems (empty = ok)."""
    problems = []
    for handler, mods in handler_modules.items():
        if not isinstance(mods, (list, tuple)):
            mods = [mods]
        for mod in mods:
            try:
                module = (importlib.import_module(mod)
                          if isinstance(mod, str) else mod)
            except Exception as e:
                problems.append(f"{handler}: import failed: {e}")
                continue
            if not any(hasattr(fn, "make_vector_cases")
                       for name, fn in inspect.getmembers(module)
                       if name.startswith("test_")):
                problems.append(f"{handler}: no exportable tests")
    return problems


def check_mods() -> list:
    """Repo-wide completeness check (reference check_mods,
    gen_from_tests/gen.py:140-203): every test module FILE under
    spec_tests/<package>/ must be reflected by its runner's handler
    registry, and every registered module must import and carry
    exportable tests.  Returns a list of problems (empty = ok)."""
    import os
    import consensus_specs_tpu.spec_tests as st

    registries = {
        "operations": ("consensus_specs_tpu.spec_tests.operations",
                       "OPERATION_HANDLERS"),
        "epoch_processing": (
            "consensus_specs_tpu.spec_tests.epoch_processing",
            "EPOCH_PROCESSING_HANDLERS"),
        "rewards": ("consensus_specs_tpu.spec_tests.rewards",
                    "REWARDS_HANDLERS"),
        "sanity": ("consensus_specs_tpu.spec_tests.sanity",
                   "SANITY_HANDLERS"),
        "fork_choice": ("consensus_specs_tpu.spec_tests.fork_choice",
                        "FORK_CHOICE_HANDLERS"),
        "genesis": ("consensus_specs_tpu.spec_tests.genesis",
                    "GENESIS_HANDLERS"),
        "transition": ("consensus_specs_tpu.spec_tests.transition",
                       "TRANSITION_HANDLERS"),
    }
    # pytest-only packages: every test is @no_vectors by design (the
    # reference excludes test/*/unittests/ from vector generation too);
    # modules must import and carry decorated tests, nothing emits
    base_units = "consensus_specs_tpu.spec_tests.unittests."
    pytest_only = {
        "unittests": [
            base_units + m for m in (
                "test_config_invariants", "test_math", "test_on_tick",
                "test_on_attestation_units", "test_validator_phase0",
                "test_validator_altair", "test_validate_merge_block",
                "test_merge_transition_units",
                "test_polynomial_commitments",
                "test_execution_requests", "test_fulu_das",
                "test_fulu_custody", "test_fulu_networking",
                "test_fulu_security", "test_misc_units",
                "test_lc_sync_protocol")],
    }

    # suites whose runners reflect them directly (module lists)
    base_random = "consensus_specs_tpu.spec_tests.random."
    base_lc = "consensus_specs_tpu.spec_tests.light_client."
    direct = {
        "finality":
            ["consensus_specs_tpu.spec_tests.finality.test_finality"],
        "random": [base_random + "test_random"] + [
            base_random + f"test_random_{fork}"
            for fork in ("phase0", "altair", "bellatrix", "capella",
                         "deneb", "electra")],
        "light_client": [
            base_lc + "test_sync",
            base_lc + "test_update_ranking",
            # data_collection is deliberately no_vectors (unit-style,
            # like the reference's pytest-only collection battery)
            base_lc + "test_data_collection",
            # reflected by the light_client runner (single_merkle_proof)
            base_lc + "test_single_merkle_proof",
            # cross-fork store upgrades; unit-style (no_vectors)
            base_lc + "test_fork_upgrades",
        ],
    }

    problems = []
    root = os.path.dirname(os.path.abspath(st.__file__))
    for pkg in sorted(os.listdir(root)):
        pkg_dir = os.path.join(root, pkg)
        if not os.path.isdir(pkg_dir) or pkg.startswith("__"):
            continue
        files = {f"consensus_specs_tpu.spec_tests.{pkg}.{f[:-3]}"
                 for f in os.listdir(pkg_dir)
                 if f.startswith("test_") and f.endswith(".py")}
        if pkg in registries:
            mod_name, attr = registries[pkg]
            registry = getattr(importlib.import_module(mod_name), attr)
            registered = set()
            for mods in registry.values():
                if not isinstance(mods, (list, tuple)):
                    mods = [mods]
                registered.update(
                    getattr(m, "__name__", m) for m in mods)
            missing = files - registered
            for m in sorted(missing):
                problems.append(
                    f"{pkg}: {m} exists but is not registered — its "
                    f"tests emit no vectors")
            problems.extend(
                f"{pkg}/{p}" for p in check_handler_modules(registry))
        elif pkg in pytest_only:
            reflected = set(pytest_only[pkg])
            for m in sorted(files - reflected):
                problems.append(
                    f"{pkg}: {m} exists but is not in the pytest-only "
                    f"registry")
            for m in sorted(reflected - files):
                problems.append(
                    f"{pkg}: registered module {m} has no file on disk")
            problems.extend(
                f"{pkg}/{p}"
                for p in check_handler_modules({pkg: pytest_only[pkg]}))
        elif pkg in direct:
            reflected = set(direct[pkg])
            missing = files - reflected
            for m in sorted(missing):
                problems.append(
                    f"{pkg}: {m} exists but the runner reflects only "
                    f"{sorted(reflected)}")
            for m in sorted(reflected - files):
                problems.append(
                    f"{pkg}: reflected module {m} has no file on disk")
            problems.extend(
                f"{pkg}/{p}"
                for p in check_handler_modules({pkg: direct[pkg]}))
        else:
            problems.append(f"unknown spec_tests package {pkg!r} — no "
                            f"runner reflects it")
    return problems
