"""Reflect decorated spec tests into vector-generator cases.

Counterpart of the reference's gen_from_tests machinery
(/root/reference/tests/core/pyspec/eth2spec/gen_helpers/gen_from_tests/
gen.py:18-61,101-116,140-203): every `@spec_state_test`-style function in a
module IS a vector case — `generate_from_tests` walks a module's test
functions and collects their `make_vector_cases` output, so pytest suites
and conformance vectors are one codebase.
"""
from __future__ import annotations

import importlib
import inspect

from .typing import TestCase, TestProvider


def generate_from_tests(runner_name: str, handler_name: str, module,
                        forks=None, presets=None, suite_name="pyspec"):
    """TestCases for every decorated test_* function in `module`."""
    if isinstance(module, str):
        module = importlib.import_module(module)
    cases: list[TestCase] = []
    for name, fn in inspect.getmembers(module):
        if not name.startswith("test_"):
            continue
        maker = getattr(fn, "make_vector_cases", None)
        if maker is None:
            continue  # plain unit test, not exported (reference check_mods)
        cases.extend(maker(runner_name, handler_name, suite_name=suite_name,
                           forks=forks, presets=presets))
    return cases


def providers_from_handlers(runner_name: str, handler_modules: dict,
                            forks=None, presets=None):
    """One TestProvider covering {handler_name: module(s)} — the shape of a
    runner main (reference run_state_test_generators)."""
    def make_cases():
        for handler, mods in handler_modules.items():
            if not isinstance(mods, (list, tuple)):
                mods = [mods]
            for mod in mods:
                yield from generate_from_tests(
                    runner_name, handler, mod, forks=forks, presets=presets)
    return [TestProvider(make_cases=make_cases)]


def check_handler_modules(handler_modules: dict) -> list:
    """Completeness check: every named module imports and contains at
    least one exportable test (reference check_mods gen.py:140-203).
    Returns a list of problems (empty = ok)."""
    problems = []
    for handler, mods in handler_modules.items():
        if not isinstance(mods, (list, tuple)):
            mods = [mods]
        for mod in mods:
            try:
                module = (importlib.import_module(mod)
                          if isinstance(mod, str) else mod)
            except Exception as e:
                problems.append(f"{handler}: import failed: {e}")
                continue
            if not any(hasattr(fn, "make_vector_cases")
                       for name, fn in inspect.getmembers(module)
                       if name.startswith("test_")):
                problems.append(f"{handler}: no exportable tests")
    return problems
