"""Light-client vectors: bootstrap objects, updates, and single merkle
proofs for the LC gindices.

Format parity with the reference's tests/generators/light_client:
- single_merkle_proof handler: object + proof.yaml for the
  current-sync-committee / finality branches
- update_ranking handler: a list of updates that must sort by
  is_better_update
- bootstrap handler: bootstrap.ssz_snappy derived from a trusted block
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...ssz import hash_tree_root
from ...test_infra import disable_bls
from ...test_infra.context import (
    _genesis_state, default_balances, default_activation_threshold)
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, state_transition_and_sign_block)

FORKS = ["altair", "capella", "deneb", "electra"]


def _lc_spec(fork):
    base = get_spec(fork, "minimal")
    overrides = {}
    for name in ["ALTAIR", "BELLATRIX", "CAPELLA", "DENEB", "ELECTRA",
                 "FULU"]:
        if base.is_post(name.lower()):
            overrides[f"{name}_FORK_EPOCH"] = 0
    return get_spec(fork, "minimal",
                    config=base.config.replace(**overrides))


def _chain(spec, n=2):
    with disable_bls():
        state = _genesis_state(spec, default_balances,
                               default_activation_threshold,
                               f"lc-{spec.fork}")
        blocks = []
        for _ in range(n):
            block = build_empty_block_for_next_slot(spec, state)
            blocks.append(
                state_transition_and_sign_block(spec, state, block))
    return state, blocks


def _bootstrap_case(fork):
    def fn():
        spec = _lc_spec(fork)
        state, blocks = _chain(spec)
        block = blocks[-1]
        bootstrap = spec.create_light_client_bootstrap(state, block)
        trusted_root = hash_tree_root(block.message)
        yield "state", state.copy()
        yield "bootstrap", bootstrap
        yield "trusted_block_root", "meta", "0x" + trusted_root.hex()
        # must initialize a store (validates header + committee branch)
        store = spec.initialize_light_client_store(trusted_root, bootstrap)
        assert store.finalized_header == bootstrap.header
    return TestCase(
        fork_name=fork, preset_name="minimal",
        runner_name="light_client", handler_name="bootstrap",
        suite_name="light_client", case_name="bootstrap_basic",
        case_fn=fn)


def providers():
    def make_cases():
        for fork in FORKS:
            yield _bootstrap_case(fork)
        # per-fork LC gindex proof batteries, reflected from the
        # dual-mode suite (reference test/*/light_client/
        # test_single_merkle_proof.py; supersedes the old hand-built
        # current_sync_committee case to avoid double emission)
        from ...spec_tests.light_client import test_single_merkle_proof \
            as lc_proofs
        for fn, suite in (
                (lc_proofs.test_current_sync_committee_merkle_proof,
                 "BeaconState"),
                (lc_proofs.test_next_sync_committee_merkle_proof,
                 "BeaconState"),
                (lc_proofs.test_finality_root_merkle_proof,
                 "BeaconState"),
                (lc_proofs.test_execution_merkle_proof,
                 "BeaconBlockBody")):
            yield from fn.make_vector_cases(
                "light_client", "single_merkle_proof", suite_name=suite)
        # step-driven sync scenarios, reflected from the dual-mode suite
        # (format tests/formats/light_client/sync.md counterpart)
        from ..reflect import generate_from_tests
        yield from generate_from_tests(
            "light_client", "sync",
            "consensus_specs_tpu.spec_tests.light_client.test_sync")
        # best-first ordered update lists
        # (format tests/formats/light_client/update_ranking.md)
        yield from generate_from_tests(
            "light_client", "update_ranking",
            "consensus_specs_tpu.spec_tests.light_client."
            "test_update_ranking")
    return [TestProvider(make_cases=make_cases)]
