"""Light-client vectors: bootstrap objects, updates, and single merkle
proofs for the LC gindices.

Format parity with the reference's tests/generators/light_client:
- single_merkle_proof handler: object + proof.yaml for the
  current-sync-committee / finality branches
- update_ranking handler: a list of updates that must sort by
  is_better_update
- bootstrap handler: bootstrap.ssz_snappy derived from a trusted block
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...ssz import hash_tree_root
from ...test_infra import disable_bls
from ...test_infra.context import (
    _genesis_state, default_balances, default_activation_threshold)
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, state_transition_and_sign_block)

FORKS = ["altair", "capella", "deneb", "electra"]


def _lc_spec(fork):
    base = get_spec(fork, "minimal")
    overrides = {}
    for name in ["ALTAIR", "BELLATRIX", "CAPELLA", "DENEB", "ELECTRA",
                 "FULU"]:
        if base.is_post(name.lower()):
            overrides[f"{name}_FORK_EPOCH"] = 0
    return get_spec(fork, "minimal",
                    config=base.config.replace(**overrides))


def _chain(spec, n=2):
    with disable_bls():
        state = _genesis_state(spec, default_balances,
                               default_activation_threshold,
                               f"lc-{spec.fork}")
        blocks = []
        for _ in range(n):
            block = build_empty_block_for_next_slot(spec, state)
            blocks.append(
                state_transition_and_sign_block(spec, state, block))
    return state, blocks


def _bootstrap_case(fork):
    def fn():
        spec = _lc_spec(fork)
        state, blocks = _chain(spec)
        block = blocks[-1]
        bootstrap = spec.create_light_client_bootstrap(state, block)
        trusted_root = hash_tree_root(block.message)
        yield "state", state.copy()
        yield "bootstrap", bootstrap
        yield "trusted_block_root", "meta", "0x" + trusted_root.hex()
        # must initialize a store (validates header + committee branch)
        store = spec.initialize_light_client_store(trusted_root, bootstrap)
        assert store.finalized_header == bootstrap.header
    return TestCase(
        fork_name=fork, preset_name="minimal",
        runner_name="light_client", handler_name="bootstrap",
        suite_name="light_client", case_name="bootstrap_basic",
        case_fn=fn)


def _sync_committee_proof_case(fork):
    def fn():
        spec = _lc_spec(fork)
        state, _blocks = _chain(spec)
        from ...ssz.proofs import (
            compute_merkle_proof, get_subtree_index,
            get_generalized_index_length)
        gindex = spec.current_sync_committee_gindex_at_slot(state.slot)
        branch = compute_merkle_proof(state, gindex)
        leaf = bytes(hash_tree_root(state.current_sync_committee))
        from ...ssz.merkle import is_valid_merkle_branch
        assert is_valid_merkle_branch(
            leaf, branch, get_generalized_index_length(gindex),
            get_subtree_index(gindex), hash_tree_root(state))
        yield "object", state.copy()
        yield "proof", "data", {
            "leaf": "0x" + leaf.hex(),
            "leaf_index": int(gindex),
            "branch": ["0x" + bytes(b).hex() for b in branch],
        }
    return TestCase(
        fork_name=fork, preset_name="minimal",
        runner_name="light_client",
        handler_name="single_merkle_proof", suite_name="BeaconState",
        case_name="current_sync_committee_merkle_proof", case_fn=fn)


def providers():
    def make_cases():
        for fork in FORKS:
            yield _bootstrap_case(fork)
            yield _sync_committee_proof_case(fork)
        # step-driven sync scenarios, reflected from the dual-mode suite
        # (format tests/formats/light_client/sync.md counterpart)
        from ..reflect import generate_from_tests
        yield from generate_from_tests(
            "light_client", "sync",
            "consensus_specs_tpu.spec_tests.light_client.test_sync")
        # best-first ordered update lists
        # (format tests/formats/light_client/update_ranking.md)
        yield from generate_from_tests(
            "light_client", "update_ranking",
            "consensus_specs_tpu.spec_tests.light_client."
            "test_update_ranking")
    return [TestProvider(make_cases=make_cases)]
