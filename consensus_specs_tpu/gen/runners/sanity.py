"""Sanity vectors (slots + blocks trajectories), reflected from the
dual-mode spec tests (spec_tests/sanity/*; format
tests/formats/sanity)."""
from ..reflect import providers_from_handlers
from ...spec_tests.sanity import SANITY_HANDLERS


def providers():
    return providers_from_handlers("sanity", SANITY_HANDLERS)
