"""Sanity vectors: whole-slot and whole-block trajectories.

Format parity with the reference's tests/generators/sanity: slots cases
yield pre + slots count + post; block cases yield pre + blocks_<i> + post.
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...test_infra import disable_bls
from ...test_infra.genesis import create_genesis_state, default_balances
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, state_transition_and_sign_block)

FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]


def _slots_case(fork, n_slots):
    def fn():
        spec = get_spec(fork, "minimal")
        with disable_bls():
            state = create_genesis_state(spec, default_balances(spec))
            yield "pre", state.copy()
            yield "slots", "meta", n_slots
            spec.process_slots(state, state.slot + n_slots)
            yield "post", state
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="sanity",
        handler_name="slots", suite_name="sanity",
        case_name=f"slots_{n_slots}", case_fn=fn)


def _blocks_case(fork, n_blocks):
    def fn():
        spec = get_spec(fork, "minimal")
        with disable_bls():
            state = create_genesis_state(spec, default_balances(spec))
            yield "pre", state.copy()
            for i in range(n_blocks):
                block = build_empty_block_for_next_slot(spec, state)
                signed = state_transition_and_sign_block(spec, state, block)
                yield f"blocks_{i}", signed
            yield "blocks_count", "meta", n_blocks
            yield "post", state
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="sanity",
        handler_name="blocks", suite_name="sanity",
        case_name=f"empty_blocks_{n_blocks}", case_fn=fn)


def providers():
    def make_cases():
        for fork in FORKS:
            for n in (1, 2):
                yield _slots_case(fork, n)
            yield _blocks_case(fork, 2)
    return [TestProvider(make_cases=make_cases)]
