"""Randomized block-trajectory vectors, reflected from the dual-mode
spec tests (spec_tests/random/test_random.py over the SHARED
test_infra/random trajectory driver — one codebase for pytest
determinism checks and emitted vectors; format: the sanity/blocks shape
pre + blocks_i + post, reference tests/generators/random)."""
from ..reflect import providers_from_handlers


def providers():
    base = "consensus_specs_tpu.spec_tests.random."
    return providers_from_handlers("random", {
        "random": [base + "test_random"] + [
            base + f"test_random_{fork}"
            for fork in ("phase0", "altair", "bellatrix", "capella",
                         "deneb", "electra")],
    })
