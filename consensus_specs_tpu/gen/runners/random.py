"""Randomized block-trajectory vectors.

Format parity with the reference's tests/generators/random (sanity/blocks
format: pre + blocks_i + post): seeded random walks interleaving empty
slots, empty blocks, attestation-carrying blocks, and epoch boundaries —
the trajectory shape of eth2spec.test.utils.randomized_block_tests.
"""
from random import Random

from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...ssz import uint64
from ...test_infra import disable_bls
from ...test_infra.context import (
    _genesis_state, default_balances, default_activation_threshold,
    MAINLINE_FORKS)
from ...test_infra.attestations import get_valid_attestation
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, next_slot,
    state_transition_and_sign_block)


def _random_block(spec, state, rng):
    block = build_empty_block_for_next_slot(spec, state)
    if rng.random() < 0.6 and state.slot >= \
            spec.MIN_ATTESTATION_INCLUSION_DELAY:
        slot = uint64(int(state.slot)
                      - int(spec.MIN_ATTESTATION_INCLUSION_DELAY) + 1)
        if slot >= spec.compute_start_slot_at_epoch(
                spec.get_current_epoch(state)):
            att = get_valid_attestation(spec, state, slot=slot,
                                        signed=True)
            block.body.attestations.append(att)
    return block


def _random_case(fork: str, seed: int, steps: int = 12):
    def fn():
        spec = get_spec(fork, "minimal")
        rng = Random(seed)
        with disable_bls():
            state = _genesis_state(spec, default_balances,
                                   default_activation_threshold, "")
            yield "pre", state.copy()
            blocks = []
            for _ in range(steps):
                roll = rng.random()
                if roll < 0.3:
                    next_slot(spec, state)
                elif roll < 0.5:
                    # leap toward the next epoch boundary
                    target = uint64(
                        int(state.slot) + int(spec.SLOTS_PER_EPOCH)
                        - int(state.slot) % int(spec.SLOTS_PER_EPOCH))
                    spec.process_slots(state, target)
                else:
                    block = _random_block(spec, state, rng)
                    blocks.append(state_transition_and_sign_block(
                        spec, state, block))
            # the sanity/blocks format replays ONLY blocks (each
            # state_transition advances slots implicitly): the trajectory
            # must END with a block or the post state is unreachable
            block = _random_block(spec, state, rng)
            blocks.append(state_transition_and_sign_block(
                spec, state, block))
            for i, sb in enumerate(blocks):
                yield f"blocks_{i}", sb
            yield "blocks_count", "meta", len(blocks)
            yield "post", state
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="random",
        handler_name="random", suite_name="random",
        case_name=f"random_{seed}", case_fn=fn)


def providers():
    def make_cases():
        for fork in MAINLINE_FORKS:
            for seed in (0, 1):
                yield _random_case(fork, seed)
    return [TestProvider(make_cases=make_cases)]
