"""Reward-component vectors (Deltas per component), reflected from the
dual-mode spec tests (spec_tests/rewards/*; format
tests/formats/rewards)."""
from ..reflect import providers_from_handlers
from ...spec_tests.rewards import REWARDS_HANDLERS


def providers():
    return providers_from_handlers("rewards", REWARDS_HANDLERS)
