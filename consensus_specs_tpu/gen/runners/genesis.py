"""Genesis initialization/validity vectors, reflected from the dual-mode
spec tests (spec_tests/genesis/*; format tests/formats/genesis)."""
from ..reflect import providers_from_handlers
from ...spec_tests.genesis import GENESIS_HANDLERS


def providers():
    return providers_from_handlers("genesis", GENESIS_HANDLERS)
