"""Networking vectors: the executable p2p helpers.

Format parity with the reference's tests/generators/networking: fulu
custody-group assignment (`get_custody_groups`,
`compute_columns_for_custody_group`) as data.yaml input/output cases,
plus phase0 subnet computation.
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec


def _custody_groups_case(node_id: int, count: int, label: str):
    def fn():
        spec = get_spec("fulu", "minimal")
        groups = spec.get_custody_groups(node_id, count)
        yield "data", "data", {
            "node_id": str(node_id),
            "custody_group_count": count,
            "result": [int(g) for g in groups],
        }
        assert len(groups) == count
        assert sorted(set(int(g) for g in groups)) == \
            sorted(int(g) for g in groups)
    return TestCase(
        fork_name="fulu", preset_name="minimal", runner_name="networking",
        handler_name="get_custody_groups", suite_name="networking",
        case_name=label, case_fn=fn)


def _custody_columns_case(group: int):
    def fn():
        spec = get_spec("fulu", "minimal")
        columns = spec.compute_columns_for_custody_group(group)
        yield "data", "data", {
            "custody_group": group,
            "result": [int(c) for c in columns],
        }
    return TestCase(
        fork_name="fulu", preset_name="minimal", runner_name="networking",
        handler_name="compute_columns_for_custody_group",
        suite_name="networking",
        case_name=f"group_{group}", case_fn=fn)


def _subnets_case(node_id: int, epoch: int):
    def fn():
        spec = get_spec("phase0", "minimal")
        subnets = spec.compute_subscribed_subnets(node_id, epoch)
        yield "data", "data", {
            "node_id": str(node_id),
            "epoch": epoch,
            "result": [int(s) for s in subnets],
        }
    return TestCase(
        fork_name="phase0", preset_name="minimal",
        runner_name="networking",
        handler_name="compute_subscribed_subnets",
        suite_name="networking",
        case_name=f"node_{node_id % 997}_epoch_{epoch}", case_fn=fn)


def providers():
    def make_cases():
        yield _custody_groups_case(0, 4, "node_zero_min_count")
        yield _custody_groups_case(2**255 - 19, 4, "node_high")
        yield _custody_groups_case(123456789, 128, "all_groups")
        for group in (0, 1, 127):
            yield _custody_columns_case(group)
        yield _subnets_case(0, 0)
        yield _subnets_case(2**200 + 7, 3)
    return [TestProvider(make_cases=make_cases)]
