"""ssz_generic vectors: per-type valid AND invalid serializations.

Format parity with the reference's tests/generators/ssz_generic (one case
module per type family; invalid cases carry only serialized.ssz_snappy and
clients must fail to decode them; valid cases carry value.yaml + root).
Handlers: uints, basic_vector, bitvector, bitlist, containers, boolean.
"""
from random import Random

from ..typing import TestCase, TestProvider
from ...debug import encode
from ...ssz import hash_tree_root
from ...ssz.types import (
    Bitlist, Bitvector, Container, List, Vector, boolean, uint8, uint16,
    uint32, uint64, uint128, uint256)


class SingleFieldContainer(Container):
    a: uint64


class SmallContainer(Container):
    a: uint16
    b: Vector[uint8, 4]


class VarContainer(Container):
    x: uint32
    data: List[uint16, 8]


def _valid_case(handler, name, obj):
    def fn():
        yield "value", "data", encode(obj)
        yield "serialized", "ssz", obj.serialize()
        # ssz_generic convention: the root lives in meta.yaml (roots.yaml
        # is the ssz_static convention)
        yield "root", "meta", "0x" + hash_tree_root(obj).hex()
    return handler, f"valid_{name}", fn


def _invalid_case(handler, name, typ, data: bytes):
    def fn():
        try:
            typ.deserialize(data)
        except (ValueError, IndexError):
            pass
        else:
            raise AssertionError(
                f"{typ.__name__} decoded invalid bytes {data.hex()!r}")
        yield "serialized", "ssz", data
    return handler, f"invalid_{name}", fn


def _cases():
    rng = Random(0x55A)
    out = []

    # uints: valid round-trips + wrong-length encodings
    for typ in (uint8, uint16, uint32, uint64, uint128, uint256):
        bits = typ.BYTE_LEN * 8
        for label, value in [("zero", 0), ("max", (1 << bits) - 1),
                             ("random", rng.randrange(1 << bits))]:
            out.append(_valid_case(
                "uints", f"uint{bits}_{label}", typ(value)))
        out.append(_invalid_case(
            "uints", f"uint{bits}_one_byte_longer", typ,
            bytes(typ.BYTE_LEN + 1)))
        out.append(_invalid_case(
            "uints", f"uint{bits}_one_byte_shorter", typ,
            bytes(max(typ.BYTE_LEN - 1, 0))))

    # boolean: only 0x00/0x01 decode
    out.append(_valid_case("boolean", "true", boolean(1)))
    out.append(_valid_case("boolean", "false", boolean(0)))
    out.append(_invalid_case("boolean", "byte_2", boolean, b"\x02"))
    out.append(_invalid_case("boolean", "empty", boolean, b""))

    # basic vectors
    v = Vector[uint64, 4]([1, 2, 3, 4])
    out.append(_valid_case("basic_vector", "vec_uint64_4", v))
    out.append(_invalid_case("basic_vector", "vec_uint64_4_extra_byte",
                             Vector[uint64, 4], v.serialize() + b"\x00"))
    out.append(_invalid_case("basic_vector", "vec_uint64_4_truncated",
                             Vector[uint64, 4], v.serialize()[:-1]))

    # bitvector / bitlist (delimiter handling)
    bv = Bitvector[10]([i % 2 == 0 for i in range(10)])
    out.append(_valid_case("bitvector", "bitvec_10", bv))
    out.append(_invalid_case("bitvector", "bitvec_10_high_padding_bit",
                             Bitvector[10], b"\xff\xff"))
    bl = Bitlist[8]([True, False, True])
    out.append(_valid_case("bitlist", "bitlist_8_len3", bl))
    out.append(_invalid_case("bitlist", "bitlist_8_no_delimiter",
                             Bitlist[8], b"\x00"))
    out.append(_invalid_case("bitlist", "bitlist_8_over_limit",
                             Bitlist[8], b"\xff\x03"))

    # containers: fixed and variable size, offset corruption
    sf = SingleFieldContainer(a=0x0123456789ABCDEF)
    out.append(_valid_case("containers", "single_field", sf))
    out.append(_invalid_case("containers", "single_field_truncated",
                             SingleFieldContainer, sf.serialize()[:-2]))
    sc = SmallContainer(a=7, b=[1, 2, 3, 4])
    out.append(_valid_case("containers", "small_fixed", sc))
    vc = VarContainer(x=9, data=[5, 6, 7])
    out.append(_valid_case("containers", "variable_list", vc))
    enc = bytearray(vc.serialize())
    enc[4] = 0xFF                       # corrupt the offset word
    out.append(_invalid_case("containers", "variable_list_bad_offset",
                             VarContainer, bytes(enc)))
    out.append(_invalid_case("containers", "variable_list_offset_cut",
                             VarContainer, vc.serialize()[:5]))

    return out


def providers():
    def make_cases():
        for handler, case_name, fn in _cases():
            yield TestCase(
                fork_name="phase0", preset_name="general",
                runner_name="ssz_generic", handler_name=handler,
                suite_name="ssz_generic", case_name=case_name, case_fn=fn)
    return [TestProvider(make_cases=make_cases)]
