"""Epoch-processing vectors (pre/post per sub-pass), reflected from the
dual-mode spec tests (spec_tests/epoch_processing/*; format
tests/formats/epoch_processing)."""
from ..reflect import providers_from_handlers
from ...spec_tests.epoch_processing import EPOCH_PROCESSING_HANDLERS


def providers():
    return providers_from_handlers(
        "epoch_processing", EPOCH_PROCESSING_HANDLERS)
