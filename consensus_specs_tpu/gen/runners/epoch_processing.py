"""Epoch-processing vectors: pre-state + one epoch sub-pass + post-state.

Format parity with the reference's tests/generators/epoch_processing.
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...test_infra import disable_bls
from ...test_infra.genesis import create_genesis_state, default_balances
from ...test_infra.blocks import next_epoch

FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]

SUB_PASSES = [
    "justification_and_finalization",
    "registry_updates",
    "slashings",
    "effective_balance_updates",
    "eth1_data_reset",
    "slashings_reset",
    "randao_mixes_reset",
]


def _case(fork, sub_pass):
    def fn():
        spec = get_spec(fork, "minimal")
        with disable_bls():
            state = create_genesis_state(spec, default_balances(spec))
            # advance into an epoch with history so the pass has work to do
            next_epoch(spec, state)
            next_epoch(spec, state)
            yield "pre", state.copy()
            getattr(spec, f"process_{sub_pass}")(state)
            yield "post", state
    return TestCase(
        fork_name=fork, preset_name="minimal",
        runner_name="epoch_processing", handler_name=sub_pass,
        suite_name="epoch_processing", case_name=f"{sub_pass}_basic",
        case_fn=fn)


def providers():
    def make_cases():
        for fork in FORKS:
            for sub_pass in SUB_PASSES:
                yield _case(fork, sub_pass)
    return [TestProvider(make_cases=make_cases)]
