"""Optimistic-sync vectors: payload-status step scripts.

Format parity with the reference's tests/generators/sync (format
tests/formats/sync: fork-choice-style steps.yaml where on_block steps
carry a payload status, plus head checks)."""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...specs.optimistic_sync import PayloadStatus
from ...ssz import hash_tree_root
from ...test_infra import disable_bls
from ...test_infra.context import (
    _genesis_state, default_balances, default_activation_threshold)
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, state_transition_and_sign_block)

FORKS = ["bellatrix", "capella", "deneb"]


def _optimistic_case(fork, invalidate: bool):
    def fn():
        spec = get_spec(fork, "minimal")
        with disable_bls():
            state = _genesis_state(spec, default_balances,
                                   default_activation_threshold,
                                   f"sync-{fork}")
            anchor_block = spec.BeaconBlock(
                state_root=hash_tree_root(state))
            store = spec.get_forkchoice_store(state, anchor_block)
            opt_store = spec.get_optimistic_store(state, anchor_block)
            yield "anchor_state", state.copy()
            yield "anchor_block", anchor_block

            steps = []
            signed_blocks = []
            for _ in range(2):
                block = build_empty_block_for_next_slot(spec, state)
                signed = state_transition_and_sign_block(
                    spec, state, block)
                signed_blocks.append(signed)
                time = (int(store.genesis_time) + int(block.slot)
                        * int(spec.config.SECONDS_PER_SLOT))
                spec.on_tick(store, time)
                steps.append({"tick": time})
                spec.on_block(store, signed)
                spec.optimistically_import_block(
                    opt_store,
                    signed.message.slot
                    + spec.SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY,
                    signed, PayloadStatus.NOT_VALIDATED)
                name = "block_" + hash_tree_root(
                    signed.message).hex()[:16]
                yield name, signed
                steps.append({"block": name,
                              "payload_status": "SYNCING"})

            tip = bytes(hash_tree_root(signed_blocks[-1].message))
            assert spec.is_optimistic_node(
                opt_store, spec.get_optimistic_head(opt_store, store))
            if invalidate:
                spec.invalidate_optimistic_block(opt_store, tip)
                steps.append({
                    "payload_status_update": {
                        "block_root": "0x" + tip.hex(),
                        "status": "INVALIDATED"}})
            else:
                spec.validate_optimistic_block(opt_store, tip)
                steps.append({
                    "payload_status_update": {
                        "block_root": "0x" + tip.hex(),
                        "status": "VALID"}})

            head = bytes(spec.get_optimistic_head(opt_store, store))
            steps.append({"checks": {
                "head": {"root": "0x" + head.hex(),
                         "slot": int(store.blocks[head].slot)}}})
            if invalidate:
                assert head != tip
            else:
                assert head == tip
            yield "steps", "data", steps
    name = "invalidated_tip" if invalidate else "all_valid"
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="sync",
        handler_name="optimistic", suite_name="optimistic_sync",
        case_name=name, case_fn=fn)


def providers():
    def make_cases():
        for fork in FORKS:
            yield _optimistic_case(fork, invalidate=False)
            yield _optimistic_case(fork, invalidate=True)
    return [TestProvider(make_cases=make_cases)]
