"""Shuffling vectors: full swap-or-not permutations per seed.

Format parity with the reference's tests/generators/shuffling/main.py:
one `mapping.yaml` per case with seed, count and the shuffled mapping.
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...utils.hash import hash as hash_eth2


def _case(spec, preset, seed, count):
    def fn():
        mapping = [int(spec.compute_shuffled_index(i, count, seed))
                   for i in range(count)]
        yield "mapping", "data", {
            "seed": "0x" + seed.hex(),
            "count": count,
            "mapping": mapping,
        }
    return TestCase(
        fork_name="phase0", preset_name=preset, runner_name="shuffling",
        handler_name="core", suite_name="shuffle",
        case_name=f"shuffle_0x{seed.hex()[:8]}_{count}", case_fn=fn)


def providers():
    def make_cases():
        for preset in ("minimal", "mainnet"):
            spec = get_spec("phase0", preset)
            for seed_i in range(4):
                seed = hash_eth2(seed_i.to_bytes(4, "little"))
                for count in (0, 1, 2, 3, 5, 8, 16, 64):
                    yield _case(spec, preset, seed, count)
    return [TestProvider(make_cases=make_cases)]
