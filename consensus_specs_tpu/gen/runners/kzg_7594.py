"""kzg_7594 (PeerDAS sampling) vectors: cells, cell proofs, recovery.

Format parity with the reference's tests/generators/kzg_7594 — each case
`data.yaml` with input/output (null output = must reject).  NOTE: cases
run on the insecure dev trusted setup (width 128; see
utils/kzg_setup_gen) so this host can compute cell proofs — byte parity
with upstream vectors requires the production 4096 setup, which is a
[--preset-list mainnet] concern for TPU runs.
"""
from functools import lru_cache
from random import Random

from ..typing import TestCase, TestProvider

WIDTH = 128
CELLS = 8


@lru_cache(maxsize=1)
def _kzg():
    from ...crypto.kzg_sampling import KZGSampling
    from ...utils.kzg_setup_gen import generate_setup
    return KZGSampling(WIDTH, WIDTH // CELLS // 2,
                       setup=generate_setup(WIDTH))


def _blob(seed: int) -> bytes:
    rng = Random(seed)
    out = b""
    for _ in range(WIDTH):
        out += (rng.randrange(1 << 200)).to_bytes(32, "big")
    return out


def _compute_cells_case(seed):
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        yield "data", "data", {
            "input": {"blob": "0x" + blob.hex()},
            "output": [["0x" + bytes(c).hex() for c in cells],
                       ["0x" + bytes(p).hex() for p in proofs]],
        }
        assert len(cells) == len(proofs)
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="compute_cells_and_kzg_proofs", suite_name="kzg",
        case_name=f"compute_cells_{seed}", case_fn=fn)


def _verify_case(seed, name, expect, tamper=False, claim_idx=None):
    """One verify_cell_kzg_proof_batch case: cells/proofs come from the
    source indices, the CLAIMED indices may differ (wrong-index cases),
    and the first cell may be tampered."""
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        commitment = kz.blob_to_kzg_commitment(blob)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        src = [0, len(cells) // 2]
        idx = claim_idx if claim_idx is not None else src
        use_cells = [cells[i] for i in src]
        if tamper:
            use_cells[0] = bytes(use_cells[0][:-32]) + b"\x00" * 31 + b"\x01"
        ok = kz.verify_cell_kzg_proof_batch(
            [commitment] * len(idx), idx, use_cells,
            [proofs[i] for i in src])
        yield "data", "data", {
            "input": {
                "commitments": ["0x" + bytes(commitment).hex()] * len(idx),
                "cell_indices": idx,
                "cells": ["0x" + bytes(c).hex() for c in use_cells],
                "proofs": ["0x" + bytes(proofs[i]).hex() for i in src],
            },
            "output": bool(ok),
        }
        assert ok is expect
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="verify_cell_kzg_proof_batch", suite_name="kzg",
        case_name=f"{name}_{seed}", case_fn=fn)


def _recover_case(seed, name, keep_fn, expect_reject=False):
    """One recover_cells_and_kzg_proofs case; keep_fn maps the cell
    count to the surviving index list.  Rejections emit output: null."""
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        keep = keep_fn(len(cells))
        payload = {"input": {"cell_indices": keep,
                             "cells": ["0x" + bytes(cells[i]).hex()
                                       for i in keep]}}
        if expect_reject:
            try:
                kz.recover_cells_and_kzg_proofs(
                    keep, [cells[i] for i in keep])
            except (AssertionError, ValueError):
                pass
            else:
                raise RuntimeError("insufficient cells accepted")
            payload["output"] = None
        else:
            rec_cells, rec_proofs = kz.recover_cells_and_kzg_proofs(
                keep, [cells[i] for i in keep])
            assert [bytes(c) for c in rec_cells] == \
                [bytes(c) for c in cells]
            assert [bytes(q) for q in rec_proofs] == \
                [bytes(q) for q in proofs]
            payload["output"] = [
                ["0x" + bytes(c).hex() for c in rec_cells],
                ["0x" + bytes(q).hex() for q in rec_proofs]]
        yield "data", "data", payload
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="recover_cells_and_kzg_proofs", suite_name="kzg",
        case_name=f"{name}_{seed}", case_fn=fn)


def _invalid_input_cases():
    """Malformed-input batteries per handler (reference kzg_7594
    invalid suites): bad blob lengths/elements, bad cell/point
    encodings, index range errors — every must-reject asserted against
    the library before emission."""
    kz = _kzg()
    blob = _blob(9)
    commitment = kz.blob_to_kzg_commitment(blob)
    cells, proofs = kz.compute_cells_and_kzg_proofs(blob)

    def must_reject(fn, *args):
        try:
            fn(*args)
        except (AssertionError, ValueError, IndexError):
            return
        raise RuntimeError("bad input accepted")

    def case(handler, name, payload):
        def fn():
            yield "data", "data", payload
        return TestCase(
            fork_name="fulu", preset_name="general",
            runner_name="kzg_7594", handler_name=handler,
            suite_name="kzg", case_name=name, case_fn=fn)

    bad_blobs = [
        ("empty", b""),
        ("short", blob[:-32]),
        ("long", blob + blob[:32]),
        ("noncanonical_element", b"\xff" * 32 + blob[32:]),
    ]
    for name, bad in bad_blobs:
        must_reject(kz.compute_cells_and_kzg_proofs, bad)
        yield case("compute_cells_and_kzg_proofs",
                   f"compute_cells_invalid_blob_{name}",
                   {"input": {"blob": "0x" + bad.hex()},
                    "output": None})

    # verify_cell_kzg_proof_batch: malformed commitment / proof / index
    bad_commitment = b"\x12" + bytes(commitment)[1:]
    must_reject(kz.verify_cell_kzg_proof_batch,
                [bad_commitment], [0], [cells[0]], [proofs[0]])
    yield case("verify_cell_kzg_proof_batch",
               "verify_invalid_commitment",
               {"input": {"row_commitments": ["0x" + bad_commitment.hex()],
                          "cell_indices": [0],
                          "cells": ["0x" + bytes(cells[0]).hex()],
                          "proofs": ["0x" + bytes(proofs[0]).hex()]},
                "output": None})
    bad_proof = b"\x12" + bytes(proofs[0])[1:]
    must_reject(kz.verify_cell_kzg_proof_batch,
                [commitment], [0], [cells[0]], [bad_proof])
    yield case("verify_cell_kzg_proof_batch", "verify_invalid_proof",
               {"input": {"row_commitments": ["0x" + commitment.hex()],
                          "cell_indices": [0],
                          "cells": ["0x" + bytes(cells[0]).hex()],
                          "proofs": ["0x" + bad_proof.hex()]},
                "output": None})
    must_reject(kz.verify_cell_kzg_proof_batch,
                [commitment], [len(cells) * 2], [cells[0]], [proofs[0]])
    yield case("verify_cell_kzg_proof_batch",
               "verify_cell_index_out_of_range",
               {"input": {"row_commitments": ["0x" + commitment.hex()],
                          "cell_indices": [len(cells) * 2],
                          "cells": ["0x" + bytes(cells[0]).hex()],
                          "proofs": ["0x" + bytes(proofs[0]).hex()]},
                "output": None})
    short_cell = bytes(cells[0])[:-1]
    must_reject(kz.verify_cell_kzg_proof_batch,
                [commitment], [0], [short_cell], [proofs[0]])
    yield case("verify_cell_kzg_proof_batch", "verify_short_cell",
               {"input": {"row_commitments": ["0x" + commitment.hex()],
                          "cell_indices": [0],
                          "cells": ["0x" + short_cell.hex()],
                          "proofs": ["0x" + bytes(proofs[0]).hex()]},
                "output": None})

    # recover: duplicate indices, out-of-range index, malformed cell
    half = len(cells) // 2
    ids = list(range(half))
    keep = [cells[i] for i in ids]
    must_reject(kz.recover_cells_and_kzg_proofs,
                [0] * half, keep)
    yield case("recover_cells_and_kzg_proofs",
               "recover_duplicate_indices",
               {"input": {"cell_indices": [0] * half,
                          "cells": ["0x" + bytes(c).hex() for c in keep]},
                "output": None})
    must_reject(kz.recover_cells_and_kzg_proofs,
                [len(cells) * 2] + ids[1:], keep)
    yield case("recover_cells_and_kzg_proofs",
               "recover_index_out_of_range",
               {"input": {"cell_indices": [len(cells) * 2] + ids[1:],
                          "cells": ["0x" + bytes(c).hex() for c in keep]},
                "output": None})
    must_reject(kz.recover_cells_and_kzg_proofs,
                ids, [bytes(keep[0])[:-1]] + keep[1:])
    yield case("recover_cells_and_kzg_proofs", "recover_short_cell",
               {"input": {"cell_indices": ids,
                          "cells": ["0x" + bytes(keep[0])[:-1].hex()]
                          + ["0x" + bytes(c).hex() for c in keep[1:]]},
                "output": None})


def providers():
    def make_cases():
        yield _compute_cells_case(1)
        yield _verify_case(2, "verify_valid", expect=True)
        yield _verify_case(3, "verify_tampered", expect=False,
                           tamper=True)
        yield _verify_case(5, "verify_wrong_index", expect=False,
                           claim_idx=[1, 2])
        yield _recover_case(4, "recover",
                            lambda n: list(range(n // 2, n)))
        yield _recover_case(6, "recover_scattered",
                            lambda n: list(range(0, n, 2)))
        yield from _invalid_input_cases()
        yield _recover_case(7, "recover_insufficient",
                            lambda n: list(range(n // 2 - 1)),
                            expect_reject=True)
    return [TestProvider(make_cases=make_cases)]
