"""kzg_7594 (PeerDAS sampling) vectors: cells, cell proofs, recovery.

Format parity with the reference's tests/generators/kzg_7594 — each case
`data.yaml` with input/output (null output = must reject).  NOTE: cases
run on the insecure dev trusted setup (width 128; see
utils/kzg_setup_gen) so this host can compute cell proofs — byte parity
with upstream vectors requires the production 4096 setup, which is a
[--preset-list mainnet] concern for TPU runs.
"""
from functools import lru_cache
from random import Random

from ..typing import TestCase, TestProvider

WIDTH = 128
CELLS = 8


@lru_cache(maxsize=1)
def _kzg():
    from ...crypto.kzg_sampling import KZGSampling
    from ...utils.kzg_setup_gen import generate_setup
    return KZGSampling(WIDTH, WIDTH // CELLS // 2,
                       setup=generate_setup(WIDTH))


def _blob(seed: int) -> bytes:
    rng = Random(seed)
    out = b""
    for _ in range(WIDTH):
        out += (rng.randrange(1 << 200)).to_bytes(32, "big")
    return out


def _compute_cells_case(seed):
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        yield "data", "data", {
            "input": {"blob": "0x" + blob.hex()},
            "output": [["0x" + bytes(c).hex() for c in cells],
                       ["0x" + bytes(p).hex() for p in proofs]],
        }
        assert len(cells) == len(proofs)
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="compute_cells_and_kzg_proofs", suite_name="kzg",
        case_name=f"compute_cells_{seed}", case_fn=fn)


def _verify_case(seed, tamper):
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        commitment = kz.blob_to_kzg_commitment(blob)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        idx = [0, len(cells) // 2]
        use_cells = [cells[i] for i in idx]
        if tamper:
            use_cells[0] = bytes(use_cells[0][:-32]) + b"\x00" * 31 + b"\x01"
        ok = kz.verify_cell_kzg_proof_batch(
            [commitment] * len(idx), idx, use_cells,
            [proofs[i] for i in idx])
        yield "data", "data", {
            "input": {
                "commitments": ["0x" + bytes(commitment).hex()] * len(idx),
                "cell_indices": idx,
                "cells": ["0x" + bytes(c).hex() for c in use_cells],
                "proofs": ["0x" + bytes(proofs[i]).hex() for i in idx],
            },
            "output": bool(ok),
        }
        assert ok is (not tamper)
    name = "verify_tampered" if tamper else "verify_valid"
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="verify_cell_kzg_proof_batch", suite_name="kzg",
        case_name=f"{name}_{seed}", case_fn=fn)


def _recover_case(seed):
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        # drop the first half; recovery needs any 50%
        keep = list(range(len(cells) // 2, len(cells)))
        rec_cells, rec_proofs = kz.recover_cells_and_kzg_proofs(
            keep, [cells[i] for i in keep])
        yield "data", "data", {
            "input": {"cell_indices": keep,
                      "cells": ["0x" + bytes(cells[i]).hex()
                                for i in keep]},
            "output": [["0x" + bytes(c).hex() for c in rec_cells],
                       ["0x" + bytes(p).hex() for p in rec_proofs]],
        }
        assert [bytes(c) for c in rec_cells] == [bytes(c) for c in cells]
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="recover_cells_and_kzg_proofs", suite_name="kzg",
        case_name=f"recover_{seed}", case_fn=fn)


def _recover_insufficient_case(seed):
    """Fewer than 50% of the cells: recovery must be rejected."""
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        cells, _proofs = kz.compute_cells_and_kzg_proofs(blob)
        keep = list(range(len(cells) // 2 - 1))   # one short of half
        try:
            kz.recover_cells_and_kzg_proofs(
                keep, [cells[i] for i in keep])
        except (AssertionError, ValueError):
            pass
        else:
            raise RuntimeError("insufficient cells accepted")
        yield "data", "data", {
            "input": {"cell_indices": keep,
                      "cells": ["0x" + bytes(cells[i]).hex()
                                for i in keep]},
            "output": None,
        }
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="recover_cells_and_kzg_proofs", suite_name="kzg",
        case_name=f"recover_insufficient_{seed}", case_fn=fn)


def _recover_scattered_case(seed):
    """Recovery from a NON-contiguous surviving set (every other
    cell)."""
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        keep = list(range(0, len(cells), 2))
        rec_cells, rec_proofs = kz.recover_cells_and_kzg_proofs(
            keep, [cells[i] for i in keep])
        assert [bytes(c) for c in rec_cells] == [bytes(c) for c in cells]
        assert [bytes(p) for p in rec_proofs] == \
            [bytes(p) for p in proofs]
        yield "data", "data", {
            "input": {"cell_indices": keep,
                      "cells": ["0x" + bytes(cells[i]).hex()
                                for i in keep]},
            "output": [["0x" + bytes(c).hex() for c in rec_cells],
                       ["0x" + bytes(p).hex() for p in rec_proofs]],
        }
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="recover_cells_and_kzg_proofs", suite_name="kzg",
        case_name=f"recover_scattered_{seed}", case_fn=fn)


def _verify_wrong_index_case(seed):
    """A valid proof presented for the WRONG cell index must fail."""
    def fn():
        kz = _kzg()
        blob = _blob(seed)
        commitment = kz.blob_to_kzg_commitment(blob)
        cells, proofs = kz.compute_cells_and_kzg_proofs(blob)
        ok = kz.verify_cell_kzg_proof_batch(
            [commitment], [1], [cells[0]], [proofs[0]])
        assert not ok
        yield "data", "data", {
            "input": {"commitments": ["0x" + bytes(commitment).hex()],
                      "cell_indices": [1],
                      "cells": ["0x" + bytes(cells[0]).hex()],
                      "proofs": ["0x" + bytes(proofs[0]).hex()]},
            "output": False,
        }
    return TestCase(
        fork_name="fulu", preset_name="general", runner_name="kzg_7594",
        handler_name="verify_cell_kzg_proof_batch", suite_name="kzg",
        case_name=f"verify_wrong_index_{seed}", case_fn=fn)


def providers():
    def make_cases():
        yield _compute_cells_case(1)
        yield _verify_case(2, tamper=False)
        yield _verify_case(3, tamper=True)
        yield _recover_case(4)
        yield _verify_wrong_index_case(5)
        yield _recover_scattered_case(6)
        yield _recover_insufficient_case(7)
    return [TestProvider(make_cases=make_cases)]
