"""Operation-processing vectors: pre-state + operation + post-state.

Format parity with the reference's tests/generators/operations: each case
dir holds pre.ssz_snappy, <operation>.ssz_snappy, and post.ssz_snappy
(absent post = expected-invalid).
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...test_infra import disable_bls
from ...test_infra.genesis import create_genesis_state, default_balances
from ...test_infra.attestations import get_valid_attestation
from ...test_infra.blocks import transition_to

FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]


def _fresh_state(spec):
    with disable_bls():
        return create_genesis_state(spec, default_balances(spec))


def _attestation_case(fork, variant):
    def fn():
        spec = get_spec(fork, "minimal")
        state = _fresh_state(spec)
        with disable_bls():
            attestation = get_valid_attestation(spec, state, signed=True)
            transition_to(spec, state,
                          state.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY)
            if variant != "valid":
                # bad target epoch: must be corrupted BEFORE the yield —
                # artifacts serialize at yield time
                attestation.data.target.epoch += 10
            yield "pre", state.copy()
            yield "attestation", attestation
            if variant == "valid":
                spec.process_attestation(state, attestation)
                yield "post", state
            else:
                try:
                    spec.process_attestation(state, attestation)
                except (AssertionError, ValueError):
                    yield "post", None
                else:
                    raise AssertionError("expected invalid attestation")
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="operations",
        handler_name="attestation", suite_name="operations",
        case_name=f"attestation_{variant}", case_fn=fn)


def _block_header_case(fork):
    def fn():
        spec = get_spec(fork, "minimal")
        state = _fresh_state(spec)
        from ...test_infra.blocks import build_empty_block_for_next_slot
        with disable_bls():
            block = build_empty_block_for_next_slot(spec, state)
            spec.process_slots(state, block.slot)
            yield "pre", state.copy()
            yield "block", block
            spec.process_block_header(state, block)
            yield "post", state
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="operations",
        handler_name="block_header", suite_name="operations",
        case_name="block_header_basic", case_fn=fn)


def providers():
    def make_cases():
        for fork in FORKS:
            for variant in ("valid", "invalid_target"):
                yield _attestation_case(fork, variant)
            yield _block_header_case(fork)
    return [TestProvider(make_cases=make_cases)]
