"""Operation-processing vectors, reflected from the dual-mode spec tests
(spec_tests/operations/*) — the reference's gen_from_tests architecture:
each pytest test body IS the vector case (format
tests/formats/operations)."""
from ..reflect import providers_from_handlers
from ...spec_tests.operations import OPERATION_HANDLERS


def providers():
    return providers_from_handlers("operations", OPERATION_HANDLERS)
