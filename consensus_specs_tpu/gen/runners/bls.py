"""BLS crypto-suite vectors, all seven reference handlers: sign, verify,
aggregate, fast_aggregate_verify, aggregate_verify,
eth_aggregate_pubkeys, eth_fast_aggregate_verify.

Format parity with the reference's tests/generators/bls/main.py: yaml
cases with {input, output}.  Deterministic private keys match the test
harness convention (small scalars).
"""
from ..typing import TestCase, TestProvider, hex_str as _hex
from ...utils import bls

PRIVKEYS = [1 + i for i in range(3)]
MESSAGES = [b"\x00" * 32, b"\x56" * 32, b"\xab" * 32]


def _yaml_case(handler, name, payload):
    def fn():
        yield "data", "data", payload
    return TestCase(
        fork_name="general", preset_name="general", runner_name="bls",
        handler_name=handler, suite_name=handler, case_name=name,
        case_fn=fn)


def _sign_cases():
    for i, sk in enumerate(PRIVKEYS):
        for j, msg in enumerate(MESSAGES):
            sig = bls.Sign(sk, msg)
            yield _yaml_case("sign", f"sign_{i}_{j}", {
                "input": {"privkey": _hex(sk.to_bytes(32, "big")),
                          "message": _hex(msg)},
                "output": _hex(sig)})


def _verify_cases():
    sk = PRIVKEYS[0]
    pk = bls.SkToPk(sk)
    msg = MESSAGES[0]
    sig = bls.Sign(sk, msg)
    yield _yaml_case("verify", "verify_valid", {
        "input": {"pubkey": _hex(pk), "message": _hex(msg),
                  "signature": _hex(sig)},
        "output": True})
    wrong = bls.Sign(PRIVKEYS[1], msg)
    yield _yaml_case("verify", "verify_wrong_key", {
        "input": {"pubkey": _hex(pk), "message": _hex(msg),
                  "signature": _hex(wrong)},
        "output": False})
    yield _yaml_case("verify", "verify_infinity_sig", {
        "input": {"pubkey": _hex(pk), "message": _hex(msg),
                  "signature": _hex(b"\xc0" + b"\x00" * 95)},
        "output": False})


def _aggregate_cases():
    msg = MESSAGES[1]
    sigs = [bls.Sign(sk, msg) for sk in PRIVKEYS]
    agg = bls.Aggregate(sigs)
    yield _yaml_case("aggregate", "aggregate_3", {
        "input": [_hex(s) for s in sigs], "output": _hex(agg)})


def _fast_aggregate_verify_cases():
    msg = MESSAGES[2]
    pks = [bls.SkToPk(sk) for sk in PRIVKEYS]
    agg = bls.Aggregate([bls.Sign(sk, msg) for sk in PRIVKEYS])
    yield _yaml_case("fast_aggregate_verify", "fav_valid", {
        "input": {"pubkeys": [_hex(p) for p in pks], "message": _hex(msg),
                  "signature": _hex(agg)},
        "output": True})
    yield _yaml_case("fast_aggregate_verify", "fav_missing_key", {
        "input": {"pubkeys": [_hex(p) for p in pks[:-1]],
                  "message": _hex(msg), "signature": _hex(agg)},
        "output": False})


def _aggregate_verify_cases():
    """Distinct (pubkey, message) pairs under one aggregate."""
    pks = [bls.SkToPk(sk) for sk in PRIVKEYS]
    sigs = [bls.Sign(sk, msg) for sk, msg in zip(PRIVKEYS, MESSAGES)]
    agg = bls.Aggregate(sigs)
    yield _yaml_case("aggregate_verify", "av_valid", {
        "input": {"pubkeys": [_hex(p) for p in pks],
                  "messages": [_hex(m) for m in MESSAGES],
                  "signature": _hex(agg)},
        "output": True})
    shuffled = [MESSAGES[1], MESSAGES[0], MESSAGES[2]]
    yield _yaml_case("aggregate_verify", "av_wrong_message_order", {
        "input": {"pubkeys": [_hex(p) for p in pks],
                  "messages": [_hex(m) for m in shuffled],
                  "signature": _hex(agg)},
        "output": False})
    yield _yaml_case("aggregate_verify", "av_empty", {
        "input": {"pubkeys": [], "messages": [],
                  "signature": _hex(b"\xc0" + b"\x00" * 95)},
        "output": False})


def _eth_aggregate_pubkeys_cases():
    """altair eth_aggregate_pubkeys: sum of pubkeys; empty list invalid."""
    pks = [bls.SkToPk(sk) for sk in PRIVKEYS]
    agg = bls.AggregatePKs(pks)
    yield _yaml_case("eth_aggregate_pubkeys", "eap_3", {
        "input": [_hex(p) for p in pks], "output": _hex(agg)})
    yield _yaml_case("eth_aggregate_pubkeys", "eap_single", {
        "input": [_hex(pks[0])], "output": _hex(pks[0])})
    yield _yaml_case("eth_aggregate_pubkeys", "eap_empty", {
        "input": [], "output": None})


def _eth_fast_aggregate_verify_cases():
    """altair variant: empty pubkeys + infinity signature is VALID."""
    msg = MESSAGES[0]
    pks = [bls.SkToPk(sk) for sk in PRIVKEYS]
    agg = bls.Aggregate([bls.Sign(sk, msg) for sk in PRIVKEYS])
    inf_sig = b"\xc0" + b"\x00" * 95
    yield _yaml_case("eth_fast_aggregate_verify", "efav_valid", {
        "input": {"pubkeys": [_hex(p) for p in pks], "message": _hex(msg),
                  "signature": _hex(agg)},
        "output": True})
    yield _yaml_case("eth_fast_aggregate_verify", "efav_empty_infinity", {
        "input": {"pubkeys": [], "message": _hex(msg),
                  "signature": _hex(inf_sig)},
        "output": True})
    yield _yaml_case("eth_fast_aggregate_verify",
                     "efav_nonempty_infinity", {
        "input": {"pubkeys": [_hex(p) for p in pks], "message": _hex(msg),
                  "signature": _hex(inf_sig)},
        "output": False})


def providers():
    def make_cases():
        yield from _sign_cases()
        yield from _verify_cases()
        yield from _aggregate_cases()
        yield from _fast_aggregate_verify_cases()
        yield from _aggregate_verify_cases()
        yield from _eth_aggregate_pubkeys_cases()
        yield from _eth_fast_aggregate_verify_cases()
    return [TestProvider(make_cases=make_cases)]
