"""BLS crypto-suite vectors, all seven reference handlers: sign, verify,
aggregate, fast_aggregate_verify, aggregate_verify,
eth_aggregate_pubkeys, eth_fast_aggregate_verify.

Case battery parity with the reference's tests/generators/bls/main.py
(:75-417): per-handler valid matrices over the reference's three
pre-generated private keys and messages, plus the edge suites — zero
privkey, tampered signatures, wrong pubkeys, zero/infinity/bad-flag
point encodings, empty input lists.  Every must-reject case asserts the
local library actually rejects before the vector is emitted.
"""
from ..typing import TestCase, TestProvider, hex_str as _hex
from ...utils import bls


def _altair():
    """The eth_ variants are SPEC functions (altair/bls.md), not shim
    primitives — the reference generator calls spec.eth_* too."""
    from ...specs import get_spec
    return get_spec("altair", "minimal")

# the reference's pre-generated keys (tests/generators/bls/main.py:45-52)
PRIVKEYS = [
    int("263dbd792f5b1be47ed85f8938c0f29586af0d3ac7b977f21c278fe1462040e3",
        16),
    int("47b8192d77bf871b62e87859d653922725724a5c031afeabc60bcef5ff665138",
        16),
    int("328388aff0d4a5b7dc9205abd374e7e98f3cd9f3418edb4eafda5fb16473d216",
        16),
]
MESSAGES = [b"\x00" * 32, b"\x56" * 32, b"\xab" * 32]
SAMPLE_MESSAGE = b"\x12" * 32

ZERO_PUBKEY = b"\x00" * 48
G1_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 47
X40_PUBKEY = b"\x40" + b"\x00" * 47
ZERO_SIGNATURE = b"\x00" * 96
G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95

PUBKEYS = [bls.SkToPk(k) for k in PRIVKEYS]


def _tamper(sig: bytes) -> bytes:
    return sig[:-4] + b"\xff\xff\xff\xff"


def _expect_exception(func, *args):
    """Narrowed to the library's rejection types so a call-convention
    bug (TypeError/AttributeError) fails loudly instead of being
    recorded as a legitimate must-reject case."""
    try:
        func(*args)
    except (AssertionError, ValueError):
        return
    raise AssertionError(f"{func.__name__} should have raised")


# deterministic decompression-failure encodings (sqrt has no root);
# shared with the kzg runner via crypto.curve
from ...crypto.curve import (          # noqa: E402
    not_on_curve_x_g1 as _not_on_curve_x_g1,
    not_on_curve_x_g2 as _not_on_curve_x_g2,
)


def _yaml_case(handler, name, payload):
    def fn():
        yield "data", "data", payload
    return TestCase(
        fork_name="general", preset_name="general", runner_name="bls",
        handler_name=handler, suite_name=handler, case_name=name,
        case_fn=fn)


def _sign_cases():
    for i, privkey in enumerate(PRIVKEYS):
        for j, message in enumerate(MESSAGES):
            sig = bls.Sign(privkey, message)
            yield _yaml_case("sign", f"sign_{i}_{j}", {
                "input": {"privkey": _hex(privkey.to_bytes(32, "big")),
                          "message": _hex(message)},
                "output": _hex(sig)})
    # privkey out of [1, r-1] is invalid (IETF BLS KeyGen)
    _R = int("73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff"
             "00000001", 16)
    for name, sk in [("zero_privkey", 0), ("privkey_equal_to_r", _R),
                     ("privkey_above_r", _R + 1),
                     ("privkey_max_u256", (1 << 256) - 1)]:
        _expect_exception(bls.Sign, sk, MESSAGES[0])
        yield _yaml_case("sign", f"sign_{name}", {
            "input": {"privkey": _hex(sk.to_bytes(32, "big")),
                      "message": _hex(MESSAGES[0])},
            "output": None})


def _verify_cases():
    for i, privkey in enumerate(PRIVKEYS):
        for j, message in enumerate(MESSAGES):
            sig = bls.Sign(privkey, message)
            pubkey = PUBKEYS[i]
            assert bls.Verify(pubkey, message, sig)
            yield _yaml_case("verify", f"verify_valid_{i}_{j}", {
                "input": {"pubkey": _hex(pubkey),
                          "message": _hex(message),
                          "signature": _hex(sig)},
                "output": True})
            wrong = PUBKEYS[(i + 1) % len(PUBKEYS)]
            assert not bls.Verify(wrong, message, sig)
            yield _yaml_case("verify", f"verify_wrong_pubkey_{i}_{j}", {
                "input": {"pubkey": _hex(wrong),
                          "message": _hex(message),
                          "signature": _hex(sig)},
                "output": False})
            tampered = _tamper(sig)
            assert not bls.Verify(pubkey, message, tampered)
            yield _yaml_case(
                "verify", f"verify_tampered_signature_{i}_{j}", {
                    "input": {"pubkey": _hex(pubkey),
                              "message": _hex(message),
                              "signature": _hex(tampered)},
                    "output": False})
    assert not bls.Verify(G1_POINT_AT_INFINITY, SAMPLE_MESSAGE,
                          G2_POINT_AT_INFINITY)
    yield _yaml_case(
        "verify", "verify_infinity_pubkey_and_infinity_signature", {
            "input": {"pubkey": _hex(G1_POINT_AT_INFINITY),
                      "message": _hex(SAMPLE_MESSAGE),
                      "signature": _hex(G2_POINT_AT_INFINITY)},
            "output": False})
    # deserialization failures must return False, not raise
    for name, pk, sig in [
            ("verify_zero_pubkey", ZERO_PUBKEY,
             bls.Sign(PRIVKEYS[0], SAMPLE_MESSAGE)),
            ("verify_x40_pubkey", X40_PUBKEY,
             bls.Sign(PRIVKEYS[0], SAMPLE_MESSAGE)),
            ("verify_zero_signature", PUBKEYS[0], ZERO_SIGNATURE),
            ("verify_garbage_signature", PUBKEYS[0], b"\xff" * 96)]:
        assert not bls.Verify(pk, SAMPLE_MESSAGE, sig)
        yield _yaml_case("verify", name, {
            "input": {"pubkey": _hex(pk),
                      "message": _hex(SAMPLE_MESSAGE),
                      "signature": _hex(sig)},
            "output": False})


def _aggregate_cases():
    for j, message in enumerate(MESSAGES):
        sigs = [bls.Sign(k, message) for k in PRIVKEYS]
        agg = bls.Aggregate(sigs)
        yield _yaml_case("aggregate", f"aggregate_{j}", {
            "input": [_hex(s) for s in sigs],
            "output": _hex(agg)})
    # empty aggregation is INVALID (IETF BLS draft-04 2.8)
    _expect_exception(bls.Aggregate, [])
    yield _yaml_case("aggregate", "aggregate_na_signatures", {
        "input": [], "output": None})
    agg = bls.Aggregate([G2_POINT_AT_INFINITY])
    assert agg == G2_POINT_AT_INFINITY
    yield _yaml_case("aggregate", "aggregate_infinity_signature", {
        "input": [_hex(G2_POINT_AT_INFINITY)],
        "output": _hex(agg)})
    single = bls.Sign(PRIVKEYS[0], SAMPLE_MESSAGE)
    assert bls.Aggregate([single]) == single
    yield _yaml_case("aggregate", "aggregate_single_signature", {
        "input": [_hex(single)], "output": _hex(single)})


def _fast_aggregate_verify_cases():
    for i, message in enumerate(MESSAGES):
        privkeys = PRIVKEYS[:i + 1]
        pubkeys = PUBKEYS[:i + 1]
        agg = bls.Aggregate([bls.Sign(k, message) for k in privkeys])
        assert bls.FastAggregateVerify(pubkeys, message, agg)
        yield _yaml_case(
            "fast_aggregate_verify", f"fast_aggregate_verify_valid_{i}", {
                "input": {"pubkeys": [_hex(p) for p in pubkeys],
                          "message": _hex(message),
                          "signature": _hex(agg)},
                "output": True})
        extra = pubkeys + [PUBKEYS[-1]]
        assert not bls.FastAggregateVerify(extra, message, agg)
        yield _yaml_case(
            "fast_aggregate_verify",
            f"fast_aggregate_verify_extra_pubkey_{i}", {
                "input": {"pubkeys": [_hex(p) for p in extra],
                          "message": _hex(message),
                          "signature": _hex(agg)},
                "output": False})
        tampered = _tamper(agg)
        assert not bls.FastAggregateVerify(pubkeys, message, tampered)
        yield _yaml_case(
            "fast_aggregate_verify",
            f"fast_aggregate_verify_tampered_signature_{i}", {
                "input": {"pubkeys": [_hex(p) for p in pubkeys],
                          "message": _hex(message),
                          "signature": _hex(tampered)},
                "output": False})
    for name, pubkeys, sig in [
            ("fast_aggregate_verify_na_pubkeys_and_infinity_signature",
             [], G2_POINT_AT_INFINITY),
            ("fast_aggregate_verify_na_pubkeys_and_zero_signature",
             [], ZERO_SIGNATURE)]:
        assert not bls.FastAggregateVerify(pubkeys, MESSAGES[-1], sig)
        yield _yaml_case("fast_aggregate_verify", name, {
            "input": {"pubkeys": [],
                      "message": _hex(MESSAGES[-1]),
                      "signature": _hex(sig)},
            "output": False})
    with_inf = PUBKEYS + [G1_POINT_AT_INFINITY]
    agg = bls.Aggregate([bls.Sign(k, SAMPLE_MESSAGE) for k in PRIVKEYS])
    assert not bls.FastAggregateVerify(with_inf, SAMPLE_MESSAGE, agg)
    yield _yaml_case(
        "fast_aggregate_verify", "fast_aggregate_verify_infinity_pubkey", {
            "input": {"pubkeys": [_hex(p) for p in with_inf],
                      "message": _hex(SAMPLE_MESSAGE),
                      "signature": _hex(agg)},
            "output": False})


def _aggregate_verify_cases():
    sigs = [bls.Sign(k, m) for k, m in zip(PRIVKEYS, MESSAGES)]
    agg = bls.Aggregate(sigs)
    assert bls.AggregateVerify(PUBKEYS, MESSAGES, agg)
    yield _yaml_case("aggregate_verify", "aggregate_verify_valid", {
        "input": {"pubkeys": [_hex(p) for p in PUBKEYS],
                  "messages": [_hex(m) for m in MESSAGES],
                  "signature": _hex(agg)},
        "output": True})
    tampered = _tamper(agg)
    assert not bls.AggregateVerify(PUBKEYS, MESSAGES, tampered)
    yield _yaml_case(
        "aggregate_verify", "aggregate_verify_tampered_signature", {
            "input": {"pubkeys": [_hex(p) for p in PUBKEYS],
                      "messages": [_hex(m) for m in MESSAGES],
                      "signature": _hex(tampered)},
            "output": False})
    swapped = [MESSAGES[1], MESSAGES[0], MESSAGES[2]]
    assert not bls.AggregateVerify(PUBKEYS, swapped, agg)
    yield _yaml_case(
        "aggregate_verify", "aggregate_verify_wrong_message_order", {
            "input": {"pubkeys": [_hex(p) for p in PUBKEYS],
                      "messages": [_hex(m) for m in swapped],
                      "signature": _hex(agg)},
            "output": False})
    for name, sig in [
            ("aggregate_verify_na_pubkeys_and_infinity_signature",
             G2_POINT_AT_INFINITY),
            ("aggregate_verify_na_pubkeys_and_zero_signature",
             ZERO_SIGNATURE)]:
        assert not bls.AggregateVerify([], [], sig)
        yield _yaml_case("aggregate_verify", name, {
            "input": {"pubkeys": [], "messages": [],
                      "signature": _hex(sig)},
            "output": False})
    with_inf = PUBKEYS + [G1_POINT_AT_INFINITY]
    with_msg = MESSAGES + [SAMPLE_MESSAGE]
    assert not bls.AggregateVerify(with_inf, with_msg, agg)
    yield _yaml_case(
        "aggregate_verify", "aggregate_verify_infinity_pubkey", {
            "input": {"pubkeys": [_hex(p) for p in with_inf],
                      "messages": [_hex(m) for m in with_msg],
                      "signature": _hex(agg)},
            "output": False})


def _eth_aggregate_pubkeys_cases():
    for i, pubkey in enumerate(PUBKEYS):
        agg = _altair().eth_aggregate_pubkeys([pubkey])
        assert agg == pubkey
        yield _yaml_case(
            "eth_aggregate_pubkeys", f"eth_aggregate_pubkeys_single_{i}", {
                "input": [_hex(pubkey)], "output": _hex(agg)})
    agg = _altair().eth_aggregate_pubkeys(PUBKEYS)
    yield _yaml_case(
        "eth_aggregate_pubkeys", "eth_aggregate_pubkeys_valid_pubkeys", {
            "input": [_hex(p) for p in PUBKEYS], "output": _hex(agg)})
    for name, pubkeys in [
            ("eth_aggregate_pubkeys_empty_list", []),
            ("eth_aggregate_pubkeys_zero_pubkey", [ZERO_PUBKEY]),
            ("eth_aggregate_pubkeys_infinity_pubkey",
             [G1_POINT_AT_INFINITY]),
            ("eth_aggregate_pubkeys_x40_pubkey", [X40_PUBKEY])]:
        _expect_exception(_altair().eth_aggregate_pubkeys, pubkeys)
        yield _yaml_case("eth_aggregate_pubkeys", name, {
            "input": [_hex(p) for p in pubkeys], "output": None})


def _eth_fast_aggregate_verify_cases():
    for i, message in enumerate(MESSAGES):
        privkeys = PRIVKEYS[:i + 1]
        pubkeys = PUBKEYS[:i + 1]
        agg = bls.Aggregate([bls.Sign(k, message) for k in privkeys])
        assert _altair().eth_fast_aggregate_verify(pubkeys, message, agg)
        yield _yaml_case(
            "eth_fast_aggregate_verify",
            f"eth_fast_aggregate_verify_valid_{i}", {
                "input": {"pubkeys": [_hex(p) for p in pubkeys],
                          "message": _hex(message),
                          "signature": _hex(agg)},
                "output": True})
        tampered = _tamper(agg)
        assert not _altair().eth_fast_aggregate_verify(pubkeys, message,
                                                 tampered)
        yield _yaml_case(
            "eth_fast_aggregate_verify",
            f"eth_fast_aggregate_verify_tampered_signature_{i}", {
                "input": {"pubkeys": [_hex(p) for p in pubkeys],
                          "message": _hex(message),
                          "signature": _hex(tampered)},
                "output": False})
    # the eth_ variant ACCEPTS the empty set with the infinity signature
    # (altair/bls.md) — the one divergence from fast_aggregate_verify
    assert _altair().eth_fast_aggregate_verify([], MESSAGES[-1],
                                         G2_POINT_AT_INFINITY)
    yield _yaml_case(
        "eth_fast_aggregate_verify",
        "eth_fast_aggregate_verify_na_pubkeys_and_infinity_signature", {
            "input": {"pubkeys": [],
                      "message": _hex(MESSAGES[-1]),
                      "signature": _hex(G2_POINT_AT_INFINITY)},
            "output": True})
    assert not _altair().eth_fast_aggregate_verify([], MESSAGES[-1],
                                             ZERO_SIGNATURE)
    yield _yaml_case(
        "eth_fast_aggregate_verify",
        "eth_fast_aggregate_verify_na_pubkeys_and_zero_signature", {
            "input": {"pubkeys": [],
                      "message": _hex(MESSAGES[-1]),
                      "signature": _hex(ZERO_SIGNATURE)},
            "output": False})
    with_inf = PUBKEYS + [G1_POINT_AT_INFINITY]
    agg = bls.Aggregate([bls.Sign(k, SAMPLE_MESSAGE) for k in PRIVKEYS])
    assert not _altair().eth_fast_aggregate_verify(with_inf, SAMPLE_MESSAGE,
                                             agg)
    yield _yaml_case(
        "eth_fast_aggregate_verify",
        "eth_fast_aggregate_verify_infinity_pubkey", {
            "input": {"pubkeys": [_hex(p) for p in with_inf],
                      "message": _hex(SAMPLE_MESSAGE),
                      "signature": _hex(agg)},
            "output": False})


# --------------------------------------------------------------------------
# deserialization hardening: every malformed encoding must be REJECTED
# (verify-family returns False; aggregate raises -> output None), like
# the reference's tampered/infinity/zero sweeps
# --------------------------------------------------------------------------

def _bad_pubkey_encodings():
    """(name, bytes) malformed G1 compressed encodings."""
    good = bytearray(PUBKEYS[0])
    x_ge_p = bytearray(good)
    x_ge_p[0] |= 0x1f
    for i in range(1, 48):
        x_ge_p[i] = 0xff
    return [
        ("zero", bytes(ZERO_PUBKEY)),
        ("infinity_with_x", b"\xc0" + b"\x00" * 46 + b"\x01"),
        ("compression_bit_unset", bytes([good[0] & 0x7f]) + bytes(good[1:])),
        ("x40_flag", bytes(X40_PUBKEY)),
        ("x_ge_modulus", bytes(x_ge_p)),
        ("not_on_curve", _not_on_curve_x_g1()),
        ("short", bytes(good[:47])),
        ("long", bytes(good) + b"\x00"),
    ]


def _bad_signature_encodings():
    sig = bytearray(bls.Sign(PRIVKEYS[0], SAMPLE_MESSAGE))
    x_ge_p = bytearray(sig)
    x_ge_p[0] |= 0x1f
    for i in range(1, 96):
        x_ge_p[i] = 0xff
    return [
        ("zero", bytes(ZERO_SIGNATURE)),
        ("infinity_with_x", b"\xc0" + b"\x00" * 94 + b"\x01"),
        ("compression_bit_unset", bytes([sig[0] & 0x7f]) + bytes(sig[1:])),
        ("x40_flag", b"\x40" + b"\x00" * 95),
        ("x_ge_modulus", bytes(x_ge_p)),
        ("not_on_curve", _not_on_curve_x_g2()),
        ("short", bytes(sig[:95])),
        ("long", bytes(sig) + b"\x00"),
    ]


def _deserialization_cases():
    sig = bls.Sign(PRIVKEYS[0], SAMPLE_MESSAGE)
    agg3 = bls.Aggregate(
        [bls.Sign(k, SAMPLE_MESSAGE) for k in PRIVKEYS])
    for name, pk in _bad_pubkey_encodings():
        assert not bls.Verify(pk, SAMPLE_MESSAGE, sig)
        yield _yaml_case("verify", f"verify_bad_pubkey_{name}", {
            "input": {"pubkey": _hex(pk),
                      "message": _hex(SAMPLE_MESSAGE),
                      "signature": _hex(sig)},
            "output": False})
        bad_list = [PUBKEYS[1], pk, PUBKEYS[2]]
        assert not bls.FastAggregateVerify(bad_list, SAMPLE_MESSAGE, agg3)
        yield _yaml_case(
            "fast_aggregate_verify",
            f"fast_aggregate_verify_bad_pubkey_{name}", {
                "input": {"pubkeys": [_hex(p) for p in bad_list],
                          "message": _hex(SAMPLE_MESSAGE),
                          "signature": _hex(agg3)},
                "output": False})
        assert not bls.AggregateVerify(
            [pk], [SAMPLE_MESSAGE], sig)
        yield _yaml_case(
            "aggregate_verify", f"aggregate_verify_bad_pubkey_{name}", {
                "input": {"pubkeys": [_hex(pk)],
                          "messages": [_hex(SAMPLE_MESSAGE)],
                          "signature": _hex(sig)},
                "output": False})
    for name, bad_sig in _bad_signature_encodings():
        assert not bls.Verify(PUBKEYS[0], SAMPLE_MESSAGE, bad_sig)
        yield _yaml_case("verify", f"verify_bad_signature_{name}", {
            "input": {"pubkey": _hex(PUBKEYS[0]),
                      "message": _hex(SAMPLE_MESSAGE),
                      "signature": _hex(bad_sig)},
            "output": False})
        assert not bls.FastAggregateVerify(
            PUBKEYS, SAMPLE_MESSAGE, bad_sig)
        yield _yaml_case(
            "fast_aggregate_verify",
            f"fast_aggregate_verify_bad_signature_{name}", {
                "input": {"pubkeys": [_hex(p) for p in PUBKEYS],
                          "message": _hex(SAMPLE_MESSAGE),
                          "signature": _hex(bad_sig)},
                "output": False})
        # Aggregate must refuse undecodable signatures
        _expect_exception(bls.Aggregate, [sig, bad_sig])
        yield _yaml_case(
            "aggregate", f"aggregate_bad_signature_{name}", {
                "input": [_hex(sig), _hex(bad_sig)],
                "output": None})


def _cross_handler_negative_cases():
    """Wrong-message / wrong-signature cross checks per verify handler."""
    agg3 = bls.Aggregate(
        [bls.Sign(k, SAMPLE_MESSAGE) for k in PRIVKEYS])
    for j, message in enumerate(MESSAGES):
        # signature over SAMPLE_MESSAGE never verifies another message
        assert not bls.FastAggregateVerify(PUBKEYS, message, agg3)
        yield _yaml_case(
            "fast_aggregate_verify",
            f"fast_aggregate_verify_wrong_message_{j}", {
                "input": {"pubkeys": [_hex(p) for p in PUBKEYS],
                          "message": _hex(message),
                          "signature": _hex(agg3)},
                "output": False})
        single = bls.Sign(PRIVKEYS[j], SAMPLE_MESSAGE)
        assert not bls.Verify(PUBKEYS[j], message, single)
        yield _yaml_case("verify", f"verify_wrong_message_{j}", {
            "input": {"pubkey": _hex(PUBKEYS[j]),
                      "message": _hex(message),
                      "signature": _hex(single)},
            "output": False})
        assert not _altair().eth_fast_aggregate_verify(
            PUBKEYS, message, agg3)
        yield _yaml_case(
            "eth_fast_aggregate_verify",
            f"eth_fast_aggregate_verify_wrong_message_{j}", {
                "input": {"pubkeys": [_hex(p) for p in PUBKEYS],
                          "message": _hex(message),
                          "signature": _hex(agg3)},
                "output": False})
    # degenerate single-signer fast aggregate == plain verify
    single_sig = bls.Sign(PRIVKEYS[0], SAMPLE_MESSAGE)
    assert bls.FastAggregateVerify([PUBKEYS[0]], SAMPLE_MESSAGE,
                                   single_sig)
    yield _yaml_case(
        "fast_aggregate_verify",
        "fast_aggregate_verify_single_pubkey", {
            "input": {"pubkeys": [_hex(PUBKEYS[0])],
                      "message": _hex(SAMPLE_MESSAGE),
                      "signature": _hex(single_sig)},
            "output": True})
    # per-position pubkey corruption in aggregate_verify
    sigs = [bls.Sign(k, m) for k, m in zip(PRIVKEYS, MESSAGES)]
    agg = bls.Aggregate(sigs)
    for pos in range(3):
        pubkeys = list(PUBKEYS)
        pubkeys[pos] = PUBKEYS[(pos + 1) % 3]
        assert not bls.AggregateVerify(pubkeys, MESSAGES, agg)
        yield _yaml_case(
            "aggregate_verify",
            f"aggregate_verify_wrong_pubkey_position_{pos}", {
                "input": {"pubkeys": [_hex(p) for p in pubkeys],
                          "messages": [_hex(m) for m in MESSAGES],
                          "signature": _hex(agg)},
                "output": False})
    # subset signatures: dropping one signer must fail the aggregate
    for drop in range(3):
        partial = bls.Aggregate(
            [s for i, s in enumerate(sigs) if i != drop])
        assert not bls.AggregateVerify(PUBKEYS, MESSAGES, partial)
        yield _yaml_case(
            "aggregate_verify",
            f"aggregate_verify_missing_signer_{drop}", {
                "input": {"pubkeys": [_hex(p) for p in PUBKEYS],
                          "messages": [_hex(m) for m in MESSAGES],
                          "signature": _hex(partial)},
                "output": False})


def providers():
    def make_cases():
        yield from _sign_cases()
        yield from _verify_cases()
        yield from _aggregate_cases()
        yield from _fast_aggregate_verify_cases()
        yield from _aggregate_verify_cases()
        yield from _eth_aggregate_pubkeys_cases()
        yield from _eth_fast_aggregate_verify_cases()
        yield from _deserialization_cases()
        yield from _cross_handler_negative_cases()
    return [TestProvider(make_cases=make_cases)]
