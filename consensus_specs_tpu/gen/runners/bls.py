"""BLS crypto-suite vectors: sign/verify/aggregate/fast_aggregate_verify.

Format parity with the reference's tests/generators/bls/main.py: yaml
cases with {input, output}.  Deterministic private keys match the test
harness convention (small scalars).
"""
from ..typing import TestCase, TestProvider, hex_str as _hex
from ...utils import bls

PRIVKEYS = [1 + i for i in range(3)]
MESSAGES = [b"\x00" * 32, b"\x56" * 32, b"\xab" * 32]


def _yaml_case(handler, name, payload):
    def fn():
        yield "data", "data", payload
    return TestCase(
        fork_name="general", preset_name="general", runner_name="bls",
        handler_name=handler, suite_name=handler, case_name=name,
        case_fn=fn)


def _sign_cases():
    for i, sk in enumerate(PRIVKEYS):
        for j, msg in enumerate(MESSAGES):
            sig = bls.Sign(sk, msg)
            yield _yaml_case("sign", f"sign_{i}_{j}", {
                "input": {"privkey": _hex(sk.to_bytes(32, "big")),
                          "message": _hex(msg)},
                "output": _hex(sig)})


def _verify_cases():
    sk = PRIVKEYS[0]
    pk = bls.SkToPk(sk)
    msg = MESSAGES[0]
    sig = bls.Sign(sk, msg)
    yield _yaml_case("verify", "verify_valid", {
        "input": {"pubkey": _hex(pk), "message": _hex(msg),
                  "signature": _hex(sig)},
        "output": True})
    wrong = bls.Sign(PRIVKEYS[1], msg)
    yield _yaml_case("verify", "verify_wrong_key", {
        "input": {"pubkey": _hex(pk), "message": _hex(msg),
                  "signature": _hex(wrong)},
        "output": False})
    yield _yaml_case("verify", "verify_infinity_sig", {
        "input": {"pubkey": _hex(pk), "message": _hex(msg),
                  "signature": _hex(b"\xc0" + b"\x00" * 95)},
        "output": False})


def _aggregate_cases():
    msg = MESSAGES[1]
    sigs = [bls.Sign(sk, msg) for sk in PRIVKEYS]
    agg = bls.Aggregate(sigs)
    yield _yaml_case("aggregate", "aggregate_3", {
        "input": [_hex(s) for s in sigs], "output": _hex(agg)})


def _fast_aggregate_verify_cases():
    msg = MESSAGES[2]
    pks = [bls.SkToPk(sk) for sk in PRIVKEYS]
    agg = bls.Aggregate([bls.Sign(sk, msg) for sk in PRIVKEYS])
    yield _yaml_case("fast_aggregate_verify", "fav_valid", {
        "input": {"pubkeys": [_hex(p) for p in pks], "message": _hex(msg),
                  "signature": _hex(agg)},
        "output": True})
    yield _yaml_case("fast_aggregate_verify", "fav_missing_key", {
        "input": {"pubkeys": [_hex(p) for p in pks[:-1]],
                  "message": _hex(msg), "signature": _hex(agg)},
        "output": False})


def providers():
    def make_cases():
        yield from _sign_cases()
        yield from _verify_cases()
        yield from _aggregate_cases()
        yield from _fast_aggregate_verify_cases()
    return [TestProvider(make_cases=make_cases)]
