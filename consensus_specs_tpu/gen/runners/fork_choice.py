"""Fork-choice step-script vectors, reflected from the dual-mode spec
tests (spec_tests/fork_choice/*; format tests/formats/fork_choice —
steps.yaml of on_tick/on_block/on_attestation/checks events plus one
ssz file per referenced object)."""
from ..reflect import providers_from_handlers
from ...spec_tests.fork_choice import FORK_CHOICE_HANDLERS


def providers():
    return providers_from_handlers("fork_choice", FORK_CHOICE_HANDLERS)
