"""KZG commitment vectors (the reference's kzg_4844 generator).

Runs on a width-64 dev trusted setup (secret=1337, same convention as the
reference's `make kzg_setups`) so pure-host generation stays fast; the
format — yaml cases with {input, output} of hex blobs/commitments/proofs —
matches tests/formats/kzg_4844.
"""
from ..typing import TestCase, TestProvider, hex_str as _hex
from ...crypto.kzg import KZG, bls_field_to_bytes
from ...utils.kzg_setup_gen import generate_setup

WIDTH = 64
SECRET = 1337

_kzg_cache = []


def _kzg() -> KZG:
    if not _kzg_cache:
        _kzg_cache.append(KZG(WIDTH, setup=generate_setup(WIDTH, SECRET)))
    return _kzg_cache[0]


def _blob(seed: int) -> bytes:
    vals = [(seed * 7919 + i * 104729) % (2 ** 200) for i in range(WIDTH)]
    return b"".join(bls_field_to_bytes(v) for v in vals)


def _yaml_case(handler, name, payload):
    def fn():
        yield "data", "data", payload
    return TestCase(
        fork_name="deneb", preset_name="general", runner_name="kzg",
        handler_name=handler, suite_name=f"kzg_{handler}",
        case_name=name, case_fn=fn)


def providers():
    def make_cases():
        kzg = _kzg()
        for seed in range(2):
            blob = _blob(seed)
            commitment = kzg.blob_to_kzg_commitment(blob)
            yield _yaml_case(
                "blob_to_kzg_commitment", f"commit_{seed}",
                {"input": {"blob": _hex(blob)}, "output": _hex(commitment)})

            z = bls_field_to_bytes(4096 + seed)
            proof, y = kzg.compute_kzg_proof(blob, z)
            yield _yaml_case(
                "compute_kzg_proof", f"proof_{seed}",
                {"input": {"blob": _hex(blob), "z": _hex(z)},
                 "output": [_hex(proof), _hex(y)]})
            yield _yaml_case(
                "verify_kzg_proof", f"verify_{seed}",
                {"input": {"commitment": _hex(commitment), "z": _hex(z),
                           "y": _hex(y), "proof": _hex(proof)},
                 "output": True})

            blob_proof = kzg.compute_blob_kzg_proof(blob, commitment)
            yield _yaml_case(
                "compute_blob_kzg_proof", f"blob_proof_{seed}",
                {"input": {"blob": _hex(blob),
                           "commitment": _hex(commitment)},
                 "output": _hex(blob_proof)})
            yield _yaml_case(
                "verify_blob_kzg_proof", f"blob_verify_{seed}",
                {"input": {"blob": _hex(blob),
                           "commitment": _hex(commitment),
                           "proof": _hex(blob_proof)},
                 "output": True})
        # negatives: wrong blob, wrong evaluation point, corrupt inputs
        blob_a, blob_b = _blob(0), _blob(1)
        commitment_a = kzg.blob_to_kzg_commitment(blob_a)
        commitment_b = kzg.blob_to_kzg_commitment(blob_b)
        proof_a = kzg.compute_blob_kzg_proof(blob_a, commitment_a)
        yield _yaml_case(
            "verify_blob_kzg_proof", "blob_verify_wrong_blob",
            {"input": {"blob": _hex(blob_b), "commitment": _hex(commitment_b),
                       "proof": _hex(proof_a)},
             "output": False})

        z = bls_field_to_bytes(4096)
        proof, y = kzg.compute_kzg_proof(blob_a, z)
        wrong_y = bls_field_to_bytes(
            (int.from_bytes(bytes(y), "big") + 1))
        yield _yaml_case(
            "verify_kzg_proof", "verify_wrong_y",
            {"input": {"commitment": _hex(commitment_a), "z": _hex(z),
                       "y": _hex(wrong_y), "proof": _hex(proof)},
             "output": False})
        # invalid (non-canonical) field element z: top bytes all 0xff
        bad_z = b"\xff" * 32
        try:
            kzg.compute_kzg_proof(blob_a, bad_z)
        except (AssertionError, ValueError):
            pass
        else:
            raise RuntimeError("non-canonical z accepted")
        yield _yaml_case(
            "compute_kzg_proof", "proof_invalid_z",
            {"input": {"blob": _hex(blob_a), "z": _hex(bad_z)},
             "output": None})
        # corrupt commitment (not on curve / wrong flag bits) — prove the
        # library actually rejects it before emitting the must-reject case
        bad_commitment = b"\x12" + bytes(commitment_a)[1:]
        try:
            kzg.verify_blob_kzg_proof(blob_a, bad_commitment, proof_a)
        except (AssertionError, ValueError):
            pass
        else:
            raise RuntimeError("corrupt commitment accepted")
        yield _yaml_case(
            "verify_blob_kzg_proof", "blob_verify_bad_commitment",
            {"input": {"blob": _hex(blob_a),
                       "commitment": _hex(bad_commitment),
                       "proof": _hex(proof_a)},
             "output": None})

        # batch verify: valid pair + order sensitivity
        proof_b = kzg.compute_blob_kzg_proof(blob_b, commitment_b)
        yield _yaml_case(
            "verify_blob_kzg_proof_batch", "batch_valid",
            {"input": {"blobs": [_hex(blob_a), _hex(blob_b)],
                       "commitments": [_hex(commitment_a),
                                       _hex(commitment_b)],
                       "proofs": [_hex(proof_a), _hex(proof_b)]},
             "output": True})
        yield _yaml_case(
            "verify_blob_kzg_proof_batch", "batch_swapped_proofs",
            {"input": {"blobs": [_hex(blob_a), _hex(blob_b)],
                       "commitments": [_hex(commitment_a),
                                       _hex(commitment_b)],
                       "proofs": [_hex(proof_b), _hex(proof_a)]},
             "output": False})
        yield from _invalid_input_cases(kzg)
    return [TestProvider(make_cases=make_cases)]


def _must_reject(fn, *args):
    """Assert the library rejects before emitting a null-output case."""
    try:
        fn(*args)
    except (AssertionError, ValueError):
        return
    raise RuntimeError(f"{getattr(fn, '__name__', fn)} accepted bad input")


def _invalid_blobs():
    """(name, bytes) malformed blobs (reference kzg_tests.py
    INVALID_BLOBS shape: wrong lengths + non-canonical field element)."""
    good = _blob(0)
    noncanon = (b"\xff" * 32) + good[32:]        # element >= BLS_MODULUS
    return [
        ("empty", b""),
        ("short", good[:-32]),
        ("long", good + good[:32]),
        ("truncated_element", good[:-1]),
        ("noncanonical_element", noncanon),
    ]


def _invalid_g1_points(kzg):
    """Malformed 48-byte G1 encodings (INVALID_G1_POINTS shape)."""
    from ...crypto.curve import not_on_curve_x_g1
    good = bytearray(kzg.blob_to_kzg_commitment(_blob(0)))
    return [
        ("zero_without_flag", b"\x00" * 48),
        ("infinity_with_x", b"\xc0" + b"\x00" * 46 + b"\x01"),
        ("x40_flag", b"\x40" + b"\x00" * 47),
        ("compression_bit_unset",
         bytes([good[0] & 0x7f]) + bytes(good[1:])),
        ("not_on_curve", not_on_curve_x_g1()),
        ("short", bytes(good[:47])),
        ("long", bytes(good) + b"\x00"),
    ]


def _invalid_field_elements():
    return [
        ("ge_modulus", b"\xff" * 32),
        ("short", b"\x01" * 31),
        ("long", b"\x01" * 33),
    ]


def _invalid_input_cases(kzg):
    """The reference's per-handler invalid-encoding batteries
    (test/utils/kzg_tests.py): every malformed blob/point/field input
    must make the handler raise -> output null."""
    blob = _blob(0)
    commitment = kzg.blob_to_kzg_commitment(blob)
    blob_proof = kzg.compute_blob_kzg_proof(blob, commitment)
    z = bls_field_to_bytes(4096)
    proof, y = kzg.compute_kzg_proof(blob, z)

    for name, bad in _invalid_blobs():
        _must_reject(kzg.blob_to_kzg_commitment, bad)
        yield _yaml_case(
            "blob_to_kzg_commitment", f"commit_invalid_blob_{name}",
            {"input": {"blob": _hex(bad)}, "output": None})
        _must_reject(kzg.compute_kzg_proof, bad, z)
        yield _yaml_case(
            "compute_kzg_proof", f"proof_invalid_blob_{name}",
            {"input": {"blob": _hex(bad), "z": _hex(z)}, "output": None})
        _must_reject(kzg.compute_blob_kzg_proof, bad, commitment)
        yield _yaml_case(
            "compute_blob_kzg_proof", f"blob_proof_invalid_blob_{name}",
            {"input": {"blob": _hex(bad), "commitment": _hex(commitment)},
             "output": None})
        _must_reject(kzg.verify_blob_kzg_proof, bad, commitment,
                     blob_proof)
        yield _yaml_case(
            "verify_blob_kzg_proof", f"blob_verify_invalid_blob_{name}",
            {"input": {"blob": _hex(bad), "commitment": _hex(commitment),
                       "proof": _hex(blob_proof)},
             "output": None})

    for name, bad in _invalid_g1_points(kzg):
        _must_reject(kzg.verify_kzg_proof, bad, z, y, proof)
        yield _yaml_case(
            "verify_kzg_proof", f"verify_invalid_commitment_{name}",
            {"input": {"commitment": _hex(bad), "z": _hex(z),
                       "y": _hex(y), "proof": _hex(proof)},
             "output": None})
        _must_reject(kzg.verify_kzg_proof, commitment, z, y, bad)
        yield _yaml_case(
            "verify_kzg_proof", f"verify_invalid_proof_{name}",
            {"input": {"commitment": _hex(commitment), "z": _hex(z),
                       "y": _hex(y), "proof": _hex(bad)},
             "output": None})
        _must_reject(kzg.verify_blob_kzg_proof, blob, commitment, bad)
        yield _yaml_case(
            "verify_blob_kzg_proof", f"blob_verify_invalid_proof_{name}",
            {"input": {"blob": _hex(blob), "commitment": _hex(commitment),
                       "proof": _hex(bad)},
             "output": None})
        _must_reject(kzg.compute_blob_kzg_proof, blob, bad)
        yield _yaml_case(
            "compute_blob_kzg_proof",
            f"blob_proof_invalid_commitment_{name}",
            {"input": {"blob": _hex(blob), "commitment": _hex(bad)},
             "output": None})

    for name, bad in _invalid_field_elements():
        _must_reject(kzg.compute_kzg_proof, blob, bad)
        yield _yaml_case(
            "compute_kzg_proof", f"proof_invalid_z_{name}",
            {"input": {"blob": _hex(blob), "z": _hex(bad)},
             "output": None})
        _must_reject(kzg.verify_kzg_proof, commitment, bad, y, proof)
        yield _yaml_case(
            "verify_kzg_proof", f"verify_invalid_z_{name}",
            {"input": {"commitment": _hex(commitment), "z": _hex(bad),
                       "y": _hex(y), "proof": _hex(proof)},
             "output": None})
        _must_reject(kzg.verify_kzg_proof, commitment, z, bad, proof)
        yield _yaml_case(
            "verify_kzg_proof", f"verify_invalid_y_{name}",
            {"input": {"commitment": _hex(commitment), "z": _hex(z),
                       "y": _hex(bad), "proof": _hex(proof)},
             "output": None})

    # batch: empty is trivially valid; length mismatches must raise
    assert kzg.verify_blob_kzg_proof_batch([], [], [])
    yield _yaml_case(
        "verify_blob_kzg_proof_batch", "batch_empty",
        {"input": {"blobs": [], "commitments": [], "proofs": []},
         "output": True})
    _must_reject(kzg.verify_blob_kzg_proof_batch, [blob], [], [])
    yield _yaml_case(
        "verify_blob_kzg_proof_batch", "batch_length_mismatch",
        {"input": {"blobs": [_hex(blob)], "commitments": [],
                   "proofs": []},
         "output": None})
    bad_blob = _invalid_blobs()[4][1]
    _must_reject(kzg.verify_blob_kzg_proof_batch, [bad_blob],
                 [commitment], [blob_proof])
    yield _yaml_case(
        "verify_blob_kzg_proof_batch", "batch_invalid_blob",
        {"input": {"blobs": [_hex(bad_blob)],
                   "commitments": [_hex(commitment)],
                   "proofs": [_hex(blob_proof)]},
         "output": None})
