"""Fork-upgrade vectors: state migration at each mainline boundary.

Format parity with the reference's tests/generators/forks (format
tests/formats/forks): `pre.ssz_snappy` (last pre-fork state),
`post.ssz_snappy` (the upgraded state), meta `fork` naming the upgrade.
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...test_infra import disable_bls
from ...test_infra.context import (
    _genesis_state, default_balances, default_activation_threshold,
    MAINLINE_FORKS)
from ...test_infra.fork_transition import do_fork, transition_until_fork


def _upgrade_case(pre_fork: str, post_fork: str, fork_epoch: int = 1):
    def fn():
        pre_spec = get_spec(pre_fork, "minimal")
        post_spec = get_spec(post_fork, "minimal")
        with disable_bls():
            state = _genesis_state(pre_spec, default_balances,
                                   default_activation_threshold, "")
            transition_until_fork(pre_spec, state, fork_epoch)
            yield "pre", state.copy()
            post, _ = do_fork(pre_spec, post_spec, state,
                              with_block=False)
        yield "fork", "meta", f"upgrade_to_{post_fork}"
        yield "post", post
        assert int(post.slot) == int(state.slot)
    return TestCase(
        fork_name=post_fork, preset_name="minimal", runner_name="forks",
        handler_name="fork", suite_name="fork",
        case_name=f"fork_{pre_fork}_to_{post_fork}", case_fn=fn)


def providers():
    def make_cases():
        for pre, post in zip(MAINLINE_FORKS, MAINLINE_FORKS[1:]):
            yield _upgrade_case(pre, post)
    return [TestProvider(make_cases=make_cases)]
