"""Test-vector runners (the reference's tests/generators/*).

Each module exposes providers(), returning a list of TestProvider.  The
RUNNERS registry drives scripts/gen_vectors.py.
"""
from importlib import import_module

RUNNER_NAMES = [
    "shuffling", "ssz_static", "operations", "epoch_processing",
    "sanity", "bls", "kzg", "rewards", "finality", "genesis",
    "fork_choice", "transition", "ssz_generic", "forks",
    "merkle_proof", "networking", "kzg_7594", "random",
    "light_client", "sync",
]


def get_providers(runner_name: str):
    if runner_name not in RUNNER_NAMES:
        raise KeyError(f"unknown runner {runner_name!r}; "
                       f"have {RUNNER_NAMES}")
    mod = import_module(f"{__name__}.{runner_name}")
    return mod.providers()
