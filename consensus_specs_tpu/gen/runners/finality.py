"""Finality trajectory vectors (pre + blocks_i + post), reflected from the
dual-mode spec tests (spec_tests/finality/*; format
tests/formats/finality)."""
from ..reflect import providers_from_handlers
from ...spec_tests.finality import FINALITY_HANDLERS


def providers():
    return providers_from_handlers("finality", FINALITY_HANDLERS)
