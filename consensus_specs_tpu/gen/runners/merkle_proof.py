"""Single-merkle-proof vectors: generalized-index branches into spec
containers.

Format parity with the reference's tests/generators/merkle_proof (format
tests/formats/merkle_proof): `object.ssz_snappy` + `proof.yaml` with
leaf, leaf_index (generalized), branch — verifiable with
is_valid_merkle_branch.
"""
from ..typing import TestCase, TestProvider
from ...specs import get_spec
from ...ssz import hash_tree_root
from ...ssz.merkle import is_valid_merkle_branch
from ...ssz.proofs import (
    compute_merkle_proof, get_generalized_index,
    get_generalized_index_length, get_subtree_index)
from ...test_infra import disable_bls
from ...test_infra.context import (
    _genesis_state, default_balances, default_activation_threshold)
from ...test_infra.blocks import build_empty_block_for_next_slot

FORKS = ["deneb", "electra", "fulu"]


def _blob_commitments_proof_case(fork):
    def fn():
        spec = get_spec(fork, "minimal")
        with disable_bls():
            state = _genesis_state(spec, default_balances,
                                   default_activation_threshold, "")
            block = build_empty_block_for_next_slot(spec, state)
        body = block.body
        gindex = get_generalized_index(
            type(body), "blob_kzg_commitments")
        branch = compute_merkle_proof(body, gindex)
        leaf = bytes(body.blob_kzg_commitments.hash_tree_root())
        depth = get_generalized_index_length(gindex)
        assert is_valid_merkle_branch(
            leaf, branch, depth, get_subtree_index(gindex),
            hash_tree_root(body))
        yield "object", body
        yield "proof", "data", {
            "leaf": "0x" + leaf.hex(),
            "leaf_index": int(gindex),
            "branch": ["0x" + bytes(b).hex() for b in branch],
        }
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="merkle_proof",
        handler_name="single_merkle_proof",
        suite_name="BeaconBlockBody",
        case_name="blob_kzg_commitments_merkle_proof", case_fn=fn)


def _finalized_root_proof_case(fork):
    def fn():
        spec = get_spec(fork, "minimal")
        with disable_bls():
            state = _genesis_state(spec, default_balances,
                                   default_activation_threshold, "")
        gindex = get_generalized_index(
            type(state), "finalized_checkpoint", "root")
        branch = compute_merkle_proof(state, gindex)
        leaf = bytes(state.finalized_checkpoint.root)
        depth = get_generalized_index_length(gindex)
        assert is_valid_merkle_branch(
            leaf, branch, depth, get_subtree_index(gindex),
            hash_tree_root(state))
        yield "object", state.copy()
        yield "proof", "data", {
            "leaf": "0x" + leaf.hex(),
            "leaf_index": int(gindex),
            "branch": ["0x" + bytes(b).hex() for b in branch],
        }
    return TestCase(
        fork_name=fork, preset_name="minimal", runner_name="merkle_proof",
        handler_name="single_merkle_proof", suite_name="BeaconState",
        case_name="finalized_root_merkle_proof", case_fn=fn)


def providers():
    # the LC gindex proof batteries emit under the light_client runner
    # (reference generators/light_client lists single_merkle_proof;
    # generators/merkle_proof carries only the deneb+ blob proofs)
    def make_cases():
        for fork in FORKS:
            yield _blob_commitments_proof_case(fork)
            yield _finalized_root_proof_case(fork)
    return [TestProvider(make_cases=make_cases)]
