"""Cross-fork transition vectors, reflected from the dual-mode spec tests
(spec_tests/transition/*; format tests/formats/transition)."""
from ..reflect import providers_from_handlers
from ...spec_tests.transition import TRANSITION_HANDLERS


def providers():
    return providers_from_handlers("transition", TRANSITION_HANDLERS)
