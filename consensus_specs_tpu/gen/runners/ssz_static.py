"""ssz_static vectors: random container instances per fork with roots.

Format parity with the reference's tests/generators/ssz_static/main.py:
per case `roots.yaml` (hash_tree_root), `serialized.ssz_snappy`, and
`value.yaml` (jsonable form).
"""
from random import Random

from ..typing import TestCase, TestProvider
from ...debug import RandomizationMode, get_random_ssz_object, encode
from ...specs import get_spec
from ...ssz import hash_tree_root
from ...ssz.types import Container

FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra",
         "fulu",
         # feature forks: their new containers (trackers, bids,
         # envelopes, witnesses) need static vectors too
         "whisk", "eip7732", "eip6800"]
MODES = [RandomizationMode.RANDOM, RandomizationMode.ZERO,
         RandomizationMode.MAX, RandomizationMode.ONE_COUNT]


def _container_types(spec):
    out = {}
    for name in dir(spec):
        t = getattr(spec, name, None)
        if isinstance(t, type) and issubclass(t, Container) \
                and t._field_names:
            out[name] = t
    return out


def _case(fork, preset, type_name, typ, mode, seed):
    def fn():
        rng = Random(seed)
        obj = get_random_ssz_object(rng, typ, max_bytes_length=256,
                                    max_list_length=4, mode=mode)
        yield "value", "data", encode(obj)
        yield "serialized", "ssz", obj.serialize()
        yield "roots", "data", {"root": "0x" + hash_tree_root(obj).hex()}
    return TestCase(
        fork_name=fork, preset_name=preset, runner_name="ssz_static",
        handler_name=type_name, suite_name=f"ssz_{mode.name.lower()}",
        case_name=f"case_{seed}", case_fn=fn)


def providers():
    def make_cases():
        for fork in FORKS:
            spec = get_spec(fork, "minimal")
            for type_name, typ in sorted(_container_types(spec).items()):
                for mode in MODES:
                    for seed in range(2):
                        yield _case(fork, "minimal", type_name, typ,
                                    mode, seed)
    return [TestProvider(make_cases=make_cases)]
