"""Dual-mode yield protocol for spec tests.

Same capability as the reference's test/utils/utils.py vector_test(): a
test body `yield`s named artifacts; under pytest the generator is drained
(only the asserts matter), under the vector generator the same run streams
each artifact to disk.

Artifact kinds:
    "meta" — scalar collected into meta.yaml
    "cfg"  — dict dumped as its own yaml file
    "data" — jsonable dumped as yaml
    "ssz"  — raw bytes written as <name>.ssz_snappy
SSZ views yielded without an explicit kind become both data (debug yaml is
skipped — the reference stopped emitting it too) and ssz bytes.
"""
from __future__ import annotations

import functools

from ..ssz.types import SSZType


class SkippedTest(Exception):
    """Raised by a test body (before its first yield) when the case is
    inapplicable under the current (fork, preset) — e.g. the minimal
    preset making two sync committees identical.  Pytest mode converts
    it to a skip; generator mode removes the case dir and counts it as
    skipped instead of silently emitting an empty vector case."""


def _classify(name, value, kind):
    if kind is not None:
        return name, kind, value
    if isinstance(value, SSZType):
        return name, "ssz", value.serialize()
    if isinstance(value, bytes):
        return name, "ssz", value
    return name, "data", value


def run_yields(fn, *args, **kwargs):
    """Drain a yielding test body, returning the list of artifact parts."""
    gen = fn(*args, **kwargs)
    if gen is None:
        return []
    parts = []
    for item in gen:
        if len(item) == 3:
            name, kind, value = item
        else:
            name, value = item
            kind = None
        if value is None:
            # `yield 'post', None` marks an expected-invalid case
            parts.append((name, "none", None))
            continue
        parts.append(_classify(name, value, kind))
    return parts


def vector_test(fn):
    """Pytest-facing wrapper: drains the yields so asserts run."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            run_yields(fn, *args, **kwargs)
        except SkippedTest as exc:
            import pytest
            pytest.skip(str(exc) or "inapplicable under this target")
    return wrapper
