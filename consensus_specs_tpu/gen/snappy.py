"""Snappy compression (block + framing format) in pure Python.

The reference writes test vectors as `.ssz_snappy` (snappy frame format,
see gen_runner.py:424-430 there, via the C python-snappy package).  That
package isn't in this image, so the codec is implemented from the public
format specs (google/snappy: format_description.txt, framing_format.txt).
The native C++ tier can later take over the hot path; this keeps the
on-disk format byte-compatible either way.

Public API: compress(data) / decompress(data) — framing format, as used
for .ssz_snappy files; compress_block / decompress_block — raw block
format.
"""
from __future__ import annotations

from .. import native as _native

# ---------------------------------------------------------------------------
# CRC-32C (Castagnoli), reflected polynomial 0x82F63B78
# ---------------------------------------------------------------------------

def _make_crc32c_table():
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _make_crc32c_table()


def crc32c(data: bytes) -> int:
    if _native.available():
        return _native.crc32c(data)
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    """Framing format masks the CRC to avoid crc-of-crc pathologies."""
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# raw block format
# ---------------------------------------------------------------------------

_MAX_OFFSET = 65535
_MIN_MATCH = 4


def _emit_literal(out: bytearray, data: bytes, start: int, end: int) -> None:
    n = end - start
    if n == 0:
        return
    if n <= 60:
        out.append((n - 1) << 2)
    else:
        length_bytes = (n - 1).to_bytes(4, "little").rstrip(b"\x00") or b"\x00"
        out.append((59 + len(length_bytes)) << 2)
        out += length_bytes
    out += data[start:end]


def compress_block(data: bytes) -> bytes:
    """Greedy hash-table LZ: copy-2 elements (2-byte offset, len 4..64).

    Routed through the native C++ tier when built (same format)."""
    if _native.available():
        return _native.snappy_compress_block(data)
    n = len(data)
    out = bytearray()
    # preamble: uncompressed length varint
    v = n
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)

    if n < _MIN_MATCH:
        _emit_literal(out, data, 0, n)
        return bytes(out)

    table: dict = {}
    i = 0
    lit_start = 0
    while i + _MIN_MATCH <= n:
        key = data[i:i + _MIN_MATCH]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= _MAX_OFFSET:
            # extend the match
            length = _MIN_MATCH
            while (i + length < n and length < 64
                   and data[cand + length] == data[i + length]):
                length += 1
            _emit_literal(out, data, lit_start, i)
            offset = i - cand
            out.append(((length - 1) << 2) | 0b10)
            out += offset.to_bytes(2, "little")
            i += length
            lit_start = i
        else:
            i += 1
    _emit_literal(out, data, lit_start, n)
    return bytes(out)


_MAX_BLOCK_OUT = 1 << 31      # sanity cap on the declared output size


def _parse_preamble(data: bytes):
    n = 0
    shift = 0
    pos = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated snappy preamble")
        if shift > 35:
            raise ValueError("oversized snappy preamble varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    if n > _MAX_BLOCK_OUT:
        raise ValueError("snappy block declares unreasonable output size")
    return n, pos


def decompress_block(data: bytes) -> bytes:
    if _native.available():
        expect, _ = _parse_preamble(data)
        return _native.snappy_decompress_block(data, expect)
    n, pos = _parse_preamble(data)
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        elem_type = tag & 0b11
        if elem_type == 0b00:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                nbytes = length - 60
                if pos + nbytes > len(data):
                    raise ValueError("truncated literal length")
                length = int.from_bytes(data[pos:pos + nbytes], "little") + 1
                pos += nbytes
            if pos + length > len(data):
                raise ValueError("truncated literal body")
            out += data[pos:pos + length]
            pos += length
        else:
            if elem_type == 0b01:  # copy, 1-byte offset
                length = ((tag >> 2) & 0b111) + 4
                if pos >= len(data):
                    raise ValueError("truncated copy-1")
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif elem_type == 0b10:  # copy, 2-byte offset
                length = (tag >> 2) + 1
                if pos + 2 > len(data):
                    raise ValueError("truncated copy-2")
                offset = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:  # copy, 4-byte offset
                length = (tag >> 2) + 1
                if pos + 4 > len(data):
                    raise ValueError("truncated copy-4")
                offset = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("bad copy offset")
            start = len(out) - offset
            if offset >= length:  # disjoint: bulk copy
                out += out[start:start + length]
            else:  # self-overlapping: byte-at-a-time
                for k in range(length):
                    out.append(out[start + k])
    if len(out) != n:
        raise ValueError(
            f"snappy length mismatch: expected {n}, got {len(out)}")
    return bytes(out)


# ---------------------------------------------------------------------------
# framing format
# ---------------------------------------------------------------------------

_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"
_MAX_FRAME_INPUT = 65536


def compress(data: bytes) -> bytes:
    """Snappy framing-format stream (the .ssz_snappy encoding)."""
    out = bytearray(_STREAM_ID)
    for i in range(0, len(data), _MAX_FRAME_INPUT) or [0]:
        chunk = data[i:i + _MAX_FRAME_INPUT]
        crc = _masked_crc(chunk).to_bytes(4, "little")
        comp = compress_block(chunk)
        if len(comp) < len(chunk):
            body = crc + comp
            out.append(0x00)  # compressed data chunk
        else:
            body = crc + chunk
            out.append(0x01)  # uncompressed data chunk
        out += len(body).to_bytes(3, "little")
        out += body
    return bytes(out)


def decompress(data: bytes) -> bytes:
    if not data.startswith(_STREAM_ID[:1]):
        raise ValueError("not a snappy framed stream")
    pos = 0
    out = bytearray()
    seen_stream_id = False
    while pos < len(data):
        if pos + 4 > len(data):
            raise ValueError("truncated chunk header")
        ctype = data[pos]
        clen = int.from_bytes(data[pos + 1:pos + 4], "little")
        pos += 4
        if pos + clen > len(data):
            raise ValueError("truncated chunk body")
        body = data[pos:pos + clen]
        pos += clen
        if ctype == 0xFF:  # stream identifier
            if body != _STREAM_ID[4:]:
                raise ValueError("bad stream identifier")
            seen_stream_id = True
        elif ctype == 0x00:  # compressed data
            if not seen_stream_id:
                raise ValueError("data chunk before stream identifier")
            crc, comp = body[:4], body[4:]
            chunk = decompress_block(comp)
            if _masked_crc(chunk).to_bytes(4, "little") != crc:
                raise ValueError("crc mismatch")
            out += chunk
        elif ctype == 0x01:  # uncompressed data
            if not seen_stream_id:
                raise ValueError("data chunk before stream identifier")
            crc, chunk = body[:4], body[4:]
            if _masked_crc(chunk).to_bytes(4, "little") != crc:
                raise ValueError("crc mismatch")
            out += chunk
        elif 0x80 <= ctype <= 0xFE:
            continue  # skippable padding
        else:
            raise ValueError(f"unknown unskippable chunk type {ctype:#x}")
    return bytes(out)
