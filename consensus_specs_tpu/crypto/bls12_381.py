"""BLS signature suite (proof-of-possession scheme) over BLS12-381.

The native backend behind consensus_specs_tpu.utils.bls — capability parity
with the reference's py_ecc/milagro/arkworks backends
(/root/reference/tests/core/pyspec/eth2spec/utils/bls.py:141-397): minimal
pubkeys in G1, signatures in G2, messages hashed with the
BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_ suite.

Raises ValueError for malformed/invalid inputs (the shim converts those to
False verdicts); programming errors propagate.
"""
from __future__ import annotations

from .fields import R, Fq12
from . import curve as cv
from .curve import Point, DecodeError
from .pairing import pairing_check as _pairing_check, miller_loop, final_exponentiation
from .hash_to_curve import hash_to_g2


def _check_sk(sk: int) -> int:
    sk = int(sk)
    if not 0 < sk < R:
        raise ValueError("secret key out of range")
    return sk


def SkToPk(sk: int) -> bytes:
    return cv.g1_to_bytes(cv.g1_generator() * _check_sk(sk))


def Sign(sk: int, message: bytes) -> bytes:
    return cv.g2_to_bytes(hash_to_g2(message) * _check_sk(sk))


def KeyValidate(pubkey: bytes) -> bool:
    try:
        p = cv.g1_from_bytes(pubkey)
    except DecodeError:
        return False
    return not p.is_infinity()


def _load_pubkey(pubkey: bytes) -> Point:
    p = cv.g1_from_bytes(pubkey)
    if p.is_infinity():
        raise ValueError("infinity pubkey")
    return p


def _load_signature(signature: bytes) -> Point:
    return cv.g2_from_bytes(signature)


def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
    try:
        pk = _load_pubkey(pubkey)
        sig = _load_signature(signature)
    except DecodeError:
        return False
    return _pairing_check([(pk, hash_to_g2(message)), (-cv.g1_generator(), sig)])


def Aggregate(signatures: list[bytes]) -> bytes:
    if not signatures:
        raise ValueError("cannot aggregate empty signature list")
    acc = cv.g2_infinity()
    for s in signatures:
        acc = acc + _load_signature(s)
    return cv.g2_to_bytes(acc)


def AggregatePKs(pubkeys: list[bytes]) -> bytes:
    if not pubkeys:
        raise ValueError("cannot aggregate empty pubkey list")
    acc = cv.g1_infinity()
    for pk in pubkeys:
        acc = acc + _load_pubkey(pk)
    return cv.g1_to_bytes(acc)


def FastAggregateVerify(pubkeys: list[bytes], message: bytes,
                        signature: bytes) -> bool:
    if not pubkeys:
        return False
    try:
        agg = cv.g1_infinity()
        for pk in pubkeys:
            agg = agg + _load_pubkey(pk)
        sig = _load_signature(signature)
    except DecodeError:
        return False
    return _pairing_check([(agg, hash_to_g2(message)),
                           (-cv.g1_generator(), sig)])


def AggregateVerify(pubkeys: list[bytes], messages: list[bytes],
                    signature: bytes) -> bool:
    if not pubkeys or len(pubkeys) != len(messages):
        return False
    try:
        pairs = [(_load_pubkey(pk), hash_to_g2(m))
                 for pk, m in zip(pubkeys, messages)]
        sig = _load_signature(signature)
    except DecodeError:
        return False
    pairs.append((-cv.g1_generator(), sig))
    return _pairing_check(pairs)


# ---------------------------------------------------------------------------
# low-level curve API (for KZG / Whisk, reference bls.py:224-392)
# Points are curve.Point objects; the spec treats them opaquely.
# ---------------------------------------------------------------------------

def add(a: Point, b: Point) -> Point:
    return a + b


def multiply(p: Point, n: int) -> Point:
    return p * int(n)


def neg(p: Point) -> Point:
    return -p


def multi_exp(points: list[Point], scalars: list[int]) -> Point:
    """Multi-scalar multiplication (naive; Pippenger on TPU is ops/msm)."""
    if not points or len(points) != len(scalars):
        raise ValueError("multi_exp: bad lengths")
    acc = Point.infinity(points[0].b)
    for p, s in zip(points, scalars):
        acc = acc + p * int(s)
    return acc


def pairing_check(values: list[tuple[Point, Point]]) -> bool:
    return _pairing_check(values)


def Z1() -> Point:
    return cv.g1_infinity()


def Z2() -> Point:
    return cv.g2_infinity()


def G1() -> Point:
    return cv.g1_generator()


def G2() -> Point:
    return cv.g2_generator()


def G1_to_bytes48(p: Point) -> bytes:
    return cv.g1_to_bytes(p)


def bytes48_to_G1(b: bytes) -> Point:
    return cv.g1_from_bytes(b, subgroup_check=False)


def G2_to_bytes96(p: Point) -> bytes:
    return cv.g2_to_bytes(p)


def bytes96_to_G2(b: bytes) -> Point:
    return cv.g2_from_bytes(b, subgroup_check=False)
