"""KZG cell proofs for data-availability sampling (PeerDAS / fulu).

From-scratch implementation of
/root/reference/specs/fulu/polynomial-commitments-sampling.md — public
methods `compute_cells_and_kzg_proofs`, `verify_cell_kzg_proof_batch`,
`recover_cells_and_kzg_proofs` plus the full helper surface (FFTs,
coefficient-form polynomial arithmetic, cosets, vanishing polynomials).

Performance design (results byte-identical to the reference's O(n^2)
algorithms, verified by differential tests):
- cell evaluations come from ONE size-2n FFT of the padded coefficient
  polynomial instead of per-point Horner (the brp slice of the extended
  domain IS the cell coset);
- the multi-proof quotient f(X)/(X^k - h^k) uses synthetic division
  (the coset vanishing polynomial has that closed form);
- coset interpolation in the batch verifier uses a small inverse FFT with
  a power-of-h unscaling instead of Lagrange interpolation.
"""
from __future__ import annotations

from functools import lru_cache

from .fields import R as BLS_MODULUS
from . import curve as cv
from .curve import msm
from .kzg import (
    KZG, FieldMath, BYTES_PER_FIELD_ELEMENT, KZG_ENDIANNESS,
    PRIMITIVE_ROOT_OF_UNITY, bit_reversal_permutation, bls_field_to_bytes,
    bytes_to_bls_field, compute_powers, hash_to_bls_field,
)

RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN = b"RCKZGCBATCH__V1_"
BYTES_PER_COMMITMENT = 48
BYTES_PER_PROOF = 48


def reverse_bits(n: int, order: int) -> int:
    """Bit-reverse `n` within log2(order) bits."""
    assert order & (order - 1) == 0
    bits = order.bit_length() - 1
    return int(format(n, f"0{bits}b")[::-1], 2) if bits else 0


@lru_cache(maxsize=16)
def compute_roots_of_unity(order: int) -> tuple:
    """Natural-order roots of unity of the given power-of-two order."""
    root = pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // order,
               BLS_MODULUS)
    assert pow(root, order, BLS_MODULUS) == 1
    assert order == 1 or pow(root, order // 2, BLS_MODULUS) != 1
    return tuple(compute_powers(root, order))


# ---------------------------------------------------------------------------
# FFTs (polynomial-commitments-sampling.md:135-197)
# ---------------------------------------------------------------------------

def _fft_field(vals, roots_of_unity):
    """Recursive reference shape, iterative implementation: evaluates the
    coefficient list `vals` on the domain (natural order)."""
    n = len(vals)
    if n == 1:
        return list(vals)
    # iterative Cooley-Tukey: bit-reverse copy, then butterfly sweeps
    out = [vals[reverse_bits(i, n)] for i in range(n)]
    m = 1
    while m < n:
        stride = n // (2 * m)
        for start in range(0, n, 2 * m):
            for k in range(m):
                w = roots_of_unity[k * stride]
                a = out[start + k]
                b = out[start + k + m] * w % BLS_MODULUS
                out[start + k] = (a + b) % BLS_MODULUS
                out[start + k + m] = (a - b) % BLS_MODULUS
        m *= 2
    return out


def fft_field(vals, roots_of_unity, inv: bool = False):
    """polynomial-commitments-sampling.md:151"""
    if inv:
        invlen = pow(len(vals), BLS_MODULUS - 2, BLS_MODULUS)
        inv_roots = list(roots_of_unity[0:1]) + list(roots_of_unity[:0:-1])
        return [x * invlen % BLS_MODULUS
                for x in _fft_field(vals, inv_roots)]
    return _fft_field(vals, roots_of_unity)


def coset_fft_field(vals, roots_of_unity, inv: bool = False):
    """FFT/IFFT over the coset g*DOMAIN with g = PRIMITIVE_ROOT_OF_UNITY
    (polynomial-commitments-sampling.md:166)."""
    def shift_vals(vals, factor):
        shift = 1
        out = []
        for v in vals:
            out.append(v * shift % BLS_MODULUS)
            shift = shift * factor % BLS_MODULUS
        return out

    shift_factor = PRIMITIVE_ROOT_OF_UNITY
    if inv:
        vals = fft_field(vals, roots_of_unity, inv)
        return shift_vals(vals, FieldMath.inverse(shift_factor))
    vals = shift_vals(vals, shift_factor)
    return fft_field(vals, roots_of_unity, inv)


# ---------------------------------------------------------------------------
# coefficient-form polynomial arithmetic (:234-338)
# ---------------------------------------------------------------------------

def add_polynomialcoeff(a, b):
    a, b = (a, b) if len(a) >= len(b) else (b, a)
    length_b = len(b)
    return [(a[i] + (b[i] if i < length_b else 0)) % BLS_MODULUS
            for i in range(len(a))]


def multiply_polynomialcoeff(a, b):
    r = [0] * (len(a) + len(b) - 1)
    for power, coef in enumerate(a):
        for j, x in enumerate(b):
            r[power + j] = (r[power + j] + coef * x) % BLS_MODULUS
    return r


def divide_polynomialcoeff(a, b):
    """Long polynomial division (:273)."""
    a = list(a)
    o = []
    apos = len(a) - 1
    bpos = len(b) - 1
    diff = apos - bpos
    inv_lead = FieldMath.inverse(b[bpos])
    while diff >= 0:
        quot = a[apos] * inv_lead % BLS_MODULUS
        o.insert(0, quot)
        for i in range(bpos, -1, -1):
            a[diff + i] = (a[diff + i] - b[i] * quot) % BLS_MODULUS
        apos -= 1
        diff -= 1
    return o


def interpolate_polynomialcoeff(xs, ys):
    """Lagrange interpolation (:295)."""
    assert len(xs) == len(ys)
    r = [0]
    for i in range(len(xs)):
        summand = [ys[i]]
        for j in range(len(ys)):
            if j != i:
                weight_adjustment = FieldMath.inverse(
                    (xs[i] - xs[j]) % BLS_MODULUS)
                summand = multiply_polynomialcoeff(
                    summand,
                    [(-weight_adjustment * xs[j]) % BLS_MODULUS,
                     weight_adjustment])
        r = add_polynomialcoeff(r, summand)
    return r


def vanishing_polynomialcoeff(xs):
    p = [1]
    for x in xs:
        p = multiply_polynomialcoeff(p, [(-x) % BLS_MODULUS, 1])
    return p


def evaluate_polynomialcoeff(polynomial_coeff, z):
    y = 0
    for coef in reversed(polynomial_coeff):
        y = (y * z + coef) % BLS_MODULUS
    return y


class KZGSampling(KZG):
    """KZG engine extended with the DAS cell-proof surface."""

    def __init__(self, field_elements_per_blob: int = 4096,
                 field_elements_per_cell: int = 64, **kwargs):
        super().__init__(field_elements_per_blob, **kwargs)
        self.fe_per_cell = field_elements_per_cell
        self.ext_width = 2 * self.width
        self.cells_per_ext_blob = self.ext_width // self.fe_per_cell
        self.bytes_per_cell = self.fe_per_cell * BYTES_PER_FIELD_ELEMENT
        assert len(self._g2_monomial_bytes) > self.fe_per_cell
        self._roots_ext_brp: tuple | None = None
        self._g1_monomial: list | None = None

    def g1_monomial(self):
        if self._g1_monomial is None:
            self._g1_monomial = [cv.g1_from_bytes(b, subgroup_check=False)
                                 for b in self._g1_monomial_bytes]
        return self._g1_monomial

    def _roots_of_unity_ext_brp(self) -> tuple:
        if self._roots_ext_brp is None:
            self._roots_ext_brp = tuple(bit_reversal_permutation(
                list(compute_roots_of_unity(self.ext_width))))
        return self._roots_ext_brp

    # -- cells <-> evals (:105-127)
    def cell_to_coset_evals(self, cell: bytes) -> list[int]:
        assert len(cell) == self.bytes_per_cell
        return [bytes_to_bls_field(
            bytes(cell)[i * 32:(i + 1) * 32])
            for i in range(self.fe_per_cell)]

    def coset_evals_to_cell(self, coset_evals: list[int]) -> bytes:
        return b"".join(bls_field_to_bytes(e) for e in coset_evals)

    # -- cosets (:484-515)
    def coset_shift_for_cell(self, cell_index: int) -> int:
        assert cell_index < self.cells_per_ext_blob
        return self._roots_of_unity_ext_brp()[
            self.fe_per_cell * cell_index]

    def coset_for_cell(self, cell_index: int) -> list[int]:
        assert cell_index < self.cells_per_ext_blob
        brp = self._roots_of_unity_ext_brp()
        return list(brp[self.fe_per_cell * cell_index:
                        self.fe_per_cell * (cell_index + 1)])

    # -- eval form -> coefficient form (:234)
    def polynomial_eval_to_coeff(self, polynomial: list[int]) -> list[int]:
        roots = compute_roots_of_unity(self.width)
        return fft_field(bit_reversal_permutation(list(polynomial)),
                         roots, inv=True)

    # -- multiproofs (:348-374)
    def compute_kzg_proof_multi_impl(self, polynomial_coeff, zs):
        """Generic Q(X) = f(X)/Z(X) path (reference shape); the batch cell
        computation below uses the closed-form fast path."""
        ys = [evaluate_polynomialcoeff(polynomial_coeff, z) for z in zs]
        denominator_poly = vanishing_polynomialcoeff(zs)
        quotient_polynomial = divide_polynomialcoeff(
            polynomial_coeff, denominator_poly)
        proof = self.g1_lincomb(
            self.g1_monomial()[:len(quotient_polynomial)],
            quotient_polynomial)
        return proof, ys

    def _divide_by_coset_vanishing(self, polynomial_coeff, shift):
        """f(X) // (X^k - shift^k) by synthetic division — the vanishing
        polynomial of the coset shift*G has this closed form."""
        k = self.fe_per_cell
        c = pow(shift, k, BLS_MODULUS)
        n = len(polynomial_coeff)
        if n <= k:
            return []
        q = [0] * (n - k)
        for i in range(n - k - 1, -1, -1):
            upper = q[i + k] if i + k < n - k else 0
            q[i] = (polynomial_coeff[i + k] + c * upper) % BLS_MODULUS
        return q

    # -- cell computation (:524-557)
    def compute_cells_and_kzg_proofs_polynomialcoeff(self, polynomial_coeff):
        # all cell evaluations via one extended-domain FFT: the brp slice
        # [k*cell : (k+1)*cell] of the extended domain IS coset_for_cell(k)
        padded = list(polynomial_coeff) \
            + [0] * (self.ext_width - len(polynomial_coeff))
        roots_ext = compute_roots_of_unity(self.ext_width)
        evals_natural = fft_field(padded, roots_ext)
        evals_brp = bit_reversal_permutation(evals_natural)

        cells, proofs = [], []
        for i in range(self.cells_per_ext_blob):
            ys = evals_brp[i * self.fe_per_cell:(i + 1) * self.fe_per_cell]
            shift = self.coset_shift_for_cell(i)
            quotient = self._divide_by_coset_vanishing(
                polynomial_coeff, shift)
            proof = self.g1_lincomb(
                self.g1_monomial()[:len(quotient)], quotient) \
                if quotient else self.g1_lincomb([], [])
            cells.append(self.coset_evals_to_cell(ys))
            proofs.append(proof)
        return cells, proofs

    def compute_cells_and_kzg_proofs(self, blob: bytes):
        """Public method (:542)."""
        assert len(blob) == BYTES_PER_FIELD_ELEMENT * self.width
        polynomial = self.blob_to_polynomial(blob)
        polynomial_coeff = self.polynomial_eval_to_coeff(polynomial)
        return self.compute_cells_and_kzg_proofs_polynomialcoeff(
            polynomial_coeff)

    # -- verification (:202-227, :379-477, :564-608)
    def compute_verify_cell_kzg_proof_batch_challenge(
            self, commitments, commitment_indices, cell_indices,
            cosets_evals, proofs) -> int:
        hashinput = RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN
        hashinput += self.width.to_bytes(8, KZG_ENDIANNESS)
        hashinput += self.fe_per_cell.to_bytes(8, KZG_ENDIANNESS)
        hashinput += len(commitments).to_bytes(8, KZG_ENDIANNESS)
        hashinput += len(cell_indices).to_bytes(8, KZG_ENDIANNESS)
        for commitment in commitments:
            hashinput += bytes(commitment)
        for k, coset_evals in enumerate(cosets_evals):
            hashinput += int(commitment_indices[k]).to_bytes(
                8, KZG_ENDIANNESS)
            hashinput += int(cell_indices[k]).to_bytes(8, KZG_ENDIANNESS)
            for coset_eval in coset_evals:
                hashinput += bls_field_to_bytes(coset_eval)
            hashinput += bytes(proofs[k])
        return hash_to_bls_field(hashinput)

    def _interpolate_coset(self, cell_index: int, coset_evals):
        """I(X) with I(coset[j]) == evals[j], via small inverse FFT.
        coset_for_cell orders points as h*g^bitrev(j), so un-brp first;
        F(X)=I(hX) has coeffs ifft(evals), then unscale by h^-i."""
        k = self.fe_per_cell
        small_roots = compute_roots_of_unity(k)
        ys_natural = [0] * k
        for j, y in enumerate(coset_evals):
            ys_natural[reverse_bits(j, k)] = y
        f_coeffs = fft_field(ys_natural, small_roots, inv=True)
        h_inv = FieldMath.inverse(self.coset_shift_for_cell(cell_index))
        scale = 1
        out = []
        for c in f_coeffs:
            out.append(c * scale % BLS_MODULUS)
            scale = scale * h_inv % BLS_MODULUS
        return out

    def verify_cell_kzg_proof_batch_impl(self, commitments,
                                         commitment_indices, cell_indices,
                                         cosets_evals, proofs) -> bool:
        """Universal verification equation (:379)."""
        assert len(commitment_indices) == len(cell_indices) \
            == len(cosets_evals) == len(proofs)
        assert len(commitments) == len(set(commitments))
        for commitment_index in commitment_indices:
            assert commitment_index < len(commitments)

        num_cells = len(cell_indices)
        n = self.fe_per_cell
        num_commitments = len(commitments)

        r = self.compute_verify_cell_kzg_proof_batch_challenge(
            commitments, commitment_indices, cell_indices, cosets_evals,
            proofs)
        r_powers = compute_powers(r, num_cells)

        proof_points = [cv.g1_from_bytes(bytes(p), subgroup_check=False)
                        for p in proofs]
        # LL = sum_k r^k proofs[k]
        ll = msm(proof_points, r_powers)
        # LR = [s^n]
        lr = cv.g2_from_bytes(self._g2_monomial_bytes[n],
                              subgroup_check=False)

        # RLC = sum_i weights[i] commitments[i]
        weights = [0] * num_commitments
        for k in range(num_cells):
            i = commitment_indices[k]
            weights[i] = (weights[i] + r_powers[k]) % BLS_MODULUS
        commitment_points = [
            cv.g1_from_bytes(bytes(c), subgroup_check=False)
            for c in commitments]
        rlc = msm(commitment_points, weights)

        # RLI = [sum_k r^k interp_k(s)]
        sum_interp_polys_coeff = [0] * n
        for k in range(num_cells):
            interp = self._interpolate_coset(cell_indices[k],
                                             cosets_evals[k])
            scaled = [c * r_powers[k] % BLS_MODULUS for c in interp]
            sum_interp_polys_coeff = add_polynomialcoeff(
                sum_interp_polys_coeff, scaled)
        rli = msm(self.g1_monomial()[:n], sum_interp_polys_coeff[:n])

        # RLP = sum_k (r^k h_k^n) proofs[k]
        weighted_r_powers = []
        for k in range(num_cells):
            h_k = self.coset_shift_for_cell(cell_indices[k])
            h_k_pow = pow(h_k, n, BLS_MODULUS)
            weighted_r_powers.append(r_powers[k] * h_k_pow % BLS_MODULUS)
        rlp = msm(proof_points, weighted_r_powers)

        rl = rlc + (-rli) + rlp

        from .pairing import pairing_check
        g2_0 = cv.g2_from_bytes(self._g2_monomial_bytes[0],
                                subgroup_check=False)
        return pairing_check([(ll, lr), (rl, -g2_0)])

    def verify_cell_kzg_proof_batch(self, commitments_bytes, cell_indices,
                                    cells, proofs_bytes) -> bool:
        """Public method (:564)."""
        assert len(commitments_bytes) == len(cells) == len(proofs_bytes) \
            == len(cell_indices)
        for commitment_bytes in commitments_bytes:
            assert len(commitment_bytes) == BYTES_PER_COMMITMENT
        for cell_index in cell_indices:
            assert cell_index < self.cells_per_ext_blob
        for cell in cells:
            assert len(cell) == self.bytes_per_cell
        for proof_bytes in proofs_bytes:
            assert len(proof_bytes) == BYTES_PER_PROOF

        # deterministic order-preserving dedup (the reference uses set())
        deduplicated = list(dict.fromkeys(bytes(c)
                                          for c in commitments_bytes))
        for c in deduplicated:
            self.validate_kzg_g1(c)
        commitment_indices = [deduplicated.index(bytes(c))
                              for c in commitments_bytes]
        cosets_evals = [self.cell_to_coset_evals(cell) for cell in cells]
        for p in proofs_bytes:
            self.validate_kzg_g1(p)
        return self.verify_cell_kzg_proof_batch_impl(
            deduplicated, commitment_indices, cell_indices, cosets_evals,
            [bytes(p) for p in proofs_bytes])

    # -- reconstruction (:615-741)
    def construct_vanishing_polynomial(self, missing_cell_indices):
        roots_of_unity_reduced = compute_roots_of_unity(
            self.cells_per_ext_blob)
        short_zero_poly = vanishing_polynomialcoeff([
            roots_of_unity_reduced[
                reverse_bits(i, self.cells_per_ext_blob)]
            for i in missing_cell_indices])
        zero_poly_coeff = [0] * self.ext_width
        for i, coeff in enumerate(short_zero_poly):
            zero_poly_coeff[i * self.fe_per_cell] = coeff
        return zero_poly_coeff

    def recover_polynomialcoeff(self, cell_indices, cosets_evals):
        """Zero-poly FFT recovery (:646)."""
        roots_ext = compute_roots_of_unity(self.ext_width)

        extended_evaluation_rbo = [0] * self.ext_width
        for cell_index, cell in zip(cell_indices, cosets_evals):
            start = cell_index * self.fe_per_cell
            extended_evaluation_rbo[start:start + self.fe_per_cell] = cell
        extended_evaluation = bit_reversal_permutation(
            extended_evaluation_rbo)

        missing_cell_indices = [
            i for i in range(self.cells_per_ext_blob)
            if i not in cell_indices]
        zero_poly_coeff = self.construct_vanishing_polynomial(
            missing_cell_indices)
        zero_poly_eval = fft_field(zero_poly_coeff, roots_ext)

        extended_evaluation_times_zero = [
            a * b % BLS_MODULUS
            for a, b in zip(zero_poly_eval, extended_evaluation)]
        extended_evaluation_times_zero_coeffs = fft_field(
            extended_evaluation_times_zero, roots_ext, inv=True)

        extended_evaluations_over_coset = coset_fft_field(
            extended_evaluation_times_zero_coeffs, roots_ext)
        zero_poly_over_coset = coset_fft_field(zero_poly_coeff, roots_ext)

        inv_zero = FieldMath.batch_inverse(zero_poly_over_coset)
        reconstructed_poly_over_coset = [
            a * b % BLS_MODULUS
            for a, b in zip(extended_evaluations_over_coset, inv_zero)]
        reconstructed_poly_coeff = coset_fft_field(
            reconstructed_poly_over_coset, roots_ext, inv=True)
        return reconstructed_poly_coeff[:self.width]

    def recover_cells_and_kzg_proofs(self, cell_indices, cells):
        """Public method (:706)."""
        assert len(cell_indices) == len(cells)
        assert self.cells_per_ext_blob / 2 <= len(cell_indices) \
            <= self.cells_per_ext_blob
        assert len(cell_indices) == len(set(cell_indices))
        for cell_index in cell_indices:
            assert cell_index < self.cells_per_ext_blob
        for cell in cells:
            assert len(cell) == self.bytes_per_cell

        cosets_evals = [self.cell_to_coset_evals(cell) for cell in cells]
        polynomial_coeff = self.recover_polynomialcoeff(
            cell_indices, cosets_evals)
        return self.compute_cells_and_kzg_proofs_polynomialcoeff(
            polynomial_coeff)


@lru_cache(maxsize=4)
def get_kzg_sampling(field_elements_per_blob: int = 4096,
                     field_elements_per_cell: int = 64) -> KZGSampling:
    return KZGSampling(field_elements_per_blob, field_elements_per_cell)
