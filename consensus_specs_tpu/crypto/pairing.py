"""Optimal ate pairing on BLS12-381.

From-scratch implementation over the fields.py tower.  G2 points are
untwisted into E(Fq12) via (x, y) -> (x/w^2, y/w^3) (w^6 = XI, derived from
the tower relations), and the Miller loop runs over the bits of |z| with
line evaluations at the G1 argument.  The final exponentiation uses the
easy (q^6-1)(q^2+1) step then the standard BLS12 x-chain hard part
(cyclotomic squarings + Frobenius maps), computing e(P,Q)^3 uniformly —
sound for every equality/is-one use (see final_exponentiation).

Verified against the production KZG trusted setup: e([tau]G1, G2) ==
e(G1, [tau]G2) for the monomial points (tests/test_bls.py).
"""
from __future__ import annotations

from .fields import Q, R, BLS_X, Fq2, Fq6, Fq12
from .curve import Point, Fq1

# |z| bits for the Miller loop
_ATE_LOOP = abs(BLS_X)

# hard-part exponent (after the easy (q^6-1)(q^2+1) step); the x-chain in
# _hard_part computes exactly m^(3*_HARD_EXP) — cubing is a bijection on the
# order-r target subgroup, so equality/is-one semantics are unchanged as
# long as every pairing goes through the same chain
_HARD_EXP = (Q**4 - Q * Q + 1) // R


def _embed_fq2(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2.zero(), Fq2.zero()), Fq6.zero())


def _embed_fq(a: int) -> Fq12:
    return Fq12(Fq6(Fq2(a, 0), Fq2.zero(), Fq2.zero()), Fq6.zero())


# w   = (0, 1) in the Fq6 pair basis;  w^2 = v;  v^3 = XI
_W = Fq12(Fq6.zero(), Fq6.one())
_W2_INV = (_W * _W).inv()
_W3_INV = (_W * _W * _W).inv()


class _P12:
    """Affine point over Fq12 (None coords = infinity)."""
    __slots__ = ("x", "y")

    def __init__(self, x: Fq12, y: Fq12):
        self.x = x
        self.y = y


def _untwist(q: Point) -> _P12:
    xa, ya = q.affine()
    return _P12(_embed_fq2(xa) * _W2_INV, _embed_fq2(ya) * _W3_INV)


def _line_eval(t: _P12, u: _P12, xp: Fq12, yp: Fq12) -> Fq12:
    """Evaluate the line through T and U (or tangent at T if T==U) at P."""
    if t.x == u.x and t.y == u.y:
        # tangent: slope = 3x^2 / 2y
        num = t.x.square()
        num = num + num + num
        den = t.y + t.y
    elif t.x == u.x:
        # vertical line
        return xp - t.x
    else:
        num = u.y - t.y
        den = u.x - t.x
    slope = num * den.inv()
    return slope * (xp - t.x) - (yp - t.y)


def _p12_add(a: _P12, b: _P12) -> _P12:
    if a.x == b.x and a.y == b.y:
        num = a.x.square()
        num = num + num + num
        den = a.y + a.y
    elif a.x == b.x:
        raise ZeroDivisionError("vertical addition in miller loop")
    else:
        num = b.y - a.y
        den = b.x - a.x
    s = num * den.inv()
    x3 = s.square() - a.x - b.x
    y3 = s * (a.x - x3) - a.y
    return _P12(x3, y3)


def miller_loop(p: Point, q: Point) -> Fq12:
    """Miller loop value f_{|z|,Q}(P); final exponentiation applied separately."""
    if p.is_infinity() or q.is_infinity():
        return Fq12.one()
    xa, ya = p.affine()
    xp, yp = _embed_fq(xa.v), _embed_fq(ya.v)
    qt = _untwist(q)
    t = _P12(qt.x, qt.y)
    f = Fq12.one()
    for bit in bin(_ATE_LOOP)[3:]:
        f = f.square() * _line_eval(t, t, xp, yp)
        t = _p12_add(t, t)
        if bit == "1":
            f = f * _line_eval(t, qt, xp, yp)
            t = _p12_add(t, qt)
    # z < 0: conjugate (differs from the true inverse by a norm-subfield
    # factor, which the final exponentiation kills)
    return f.conjugate()


def _exp_by_neg_x(m: Fq12) -> Fq12:
    """m^x for the (negative) BLS parameter x, m unitary: square-and-multiply
    by |x| with cyclotomic squarings, then conjugate."""
    acc = m
    for bit in bin(_ATE_LOOP)[3:]:
        acc = acc.cyclotomic_square()
        if bit == "1":
            acc = acc * m
    return acc.conjugate()


def _hard_part(m: Fq12) -> Fq12:
    """m^(3 * (q^4 - q^2 + 1) / r) by the standard BLS12 addition chain
    (5 exp-by-x + 3 Frobenius; verified symbolically in
    tests/test_bls.py::test_hard_part_chain_exponent)."""
    t2 = m
    t1 = t2.cyclotomic_square().conjugate()      # m^-2
    t3 = _exp_by_neg_x(t2)                       # m^x
    t4 = t3.cyclotomic_square()                  # m^2x
    t5 = t1 * t3                                 # m^(x-2)
    t1 = _exp_by_neg_x(t5)                       # m^(x^2-2x)
    t0 = _exp_by_neg_x(t1)                       # m^(x^3-2x^2)
    t6 = _exp_by_neg_x(t0)                       # m^(x^4-2x^3)
    t6 = t6 * t4                                 # m^(x^4-2x^3+2x)
    t4 = _exp_by_neg_x(t6)
    t5 = t5.conjugate()
    t4 = t4 * t5 * t2
    t5 = t2.conjugate()
    t1 = t1 * t2                                 # m^(x^2-2x+1)
    t1 = t1.frobenius(3)
    t6 = t6 * t5
    t6 = t6.frobenius(1)
    t3 = t3 * t0
    t3 = t3.frobenius(2)
    t3 = t3 * t1
    t3 = t3 * t6
    return t3 * t4


def final_exponentiation(f: Fq12) -> Fq12:
    """f^(3 * (q^12 - 1) / r): easy part then the x-chain hard part.

    The extra factor of 3 (inherent to the chain) is harmless: pairing
    values live in the order-r subgroup where cubing is a bijection, so
    e(P,Q)-equality and is-one checks are unaffected.
    """
    f1 = f.conjugate() * f.inv()                 # f^(q^6-1)
    m = f1.frobenius(2) * f1                     # ^(q^2+1): now unitary
    return _hard_part(m)


def pairing(p: Point, q: Point) -> Fq12:
    """e(P, Q) for P in G1, Q in G2."""
    return final_exponentiation(miller_loop(p, q))


def pairing_check(pairs: list[tuple[Point, Point]]) -> bool:
    """prod e(P_i, Q_i) == 1, with a single shared final exponentiation."""
    f = Fq12.one()
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return final_exponentiation(f).is_one()
