"""Hash-to-curve for G2: BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_.

RFC 9380 pipeline: expand_message_xmd(SHA-256) -> hash_to_field(Fq2, m=2,
L=64) -> simplified SSWU onto the 3-isogenous curve E2' (A'=240u,
B'=1012(1+u), Z=-(2+u)) -> 3-isogeny map to E2 -> clear cofactor by h_eff.

The isogeny-map coefficients are structurally verified at import: a wrong
coefficient would send SSWU outputs (which provably lie on E2') off E2, and
tests assert curve membership for random inputs.  RFC cross-vectors are not
available in this offline environment; the map is additionally pinned by the
subgroup checks and signature round-trips in tests/test_bls.py.
"""
from __future__ import annotations

import hashlib

from .fields import Q, Fq2
from .curve import Point, B2, g2_infinity

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# E2' (3-isogenous curve): y^2 = x^3 + A'x + B'
_A = Fq2(0, 240)
_B = Fq2(1012, 1012)
_Z = Fq2(-2, -1)

# effective cofactor for G2 cofactor clearing (h_eff)
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551

# 3-isogeny map coefficients (x_num, x_den, y_num, y_den), ascending powers
_XNUM = (
    Fq2(0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6),
    Fq2(0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    Fq2(0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    Fq2(0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0),
)
_XDEN = (
    Fq2(0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    Fq2(0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    Fq2.one(),  # monic degree 2
)
_YNUM = (
    Fq2(0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    Fq2(0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    Fq2(0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    Fq2(0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0),
)
_YDEN = (
    Fq2(0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    Fq2(0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    Fq2(0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
    Fq2.one(),  # monic degree 3
)


# ---------------------------------------------------------------------------
# expand_message_xmd / hash_to_field  (RFC 9380 §5)
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        raise ValueError("DST too long")
    b_in_bytes = 32   # SHA-256 output
    s_in_bytes = 64   # SHA-256 block
    ell = -(-len_in_bytes // b_in_bytes)
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * s_in_bytes
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = b
    prev = b
    for i in range(2, ell + 1):
        x = bytes(a ^ c for a, c in zip(b0, prev))
        prev = hashlib.sha256(x + bytes([i]) + dst_prime).digest()
        out += prev
    return out[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = DST_G2) -> list[Fq2]:
    L = 64
    m = 2
    data = expand_message_xmd(msg, dst, count * m * L)
    out = []
    for i in range(count):
        coords = []
        for j in range(m):
            off = L * (j + i * m)
            coords.append(int.from_bytes(data[off:off + L], "big") % Q)
        out.append(Fq2(coords[0], coords[1]))
    return out


# ---------------------------------------------------------------------------
# simplified SSWU onto E2'  (RFC 9380 §6.6.2)
# ---------------------------------------------------------------------------

def _g_prime(x: Fq2) -> Fq2:
    return x.square() * x + _A * x + _B


def sswu_map(u: Fq2) -> tuple[Fq2, Fq2]:
    """Map a field element to a point on E2' (not E2!)."""
    u2 = u.square()
    tv1 = _Z * u2
    tv2 = tv1.square() + tv1          # Z^2 u^4 + Z u^2
    if tv2.is_zero():
        x1 = _B * (_Z * _A).inv()     # exceptional case
    else:
        x1 = (-_B) * _A.inv() * (Fq2.one() + tv2.inv())
    gx1 = _g_prime(x1)
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = tv1 * x1
        gx2 = _g_prime(x2)
        y2 = gx2.sqrt()
        assert y2 is not None, "SSWU: neither gx1 nor gx2 is square"
        x, y = x2, y2
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


def iso_map(x: Fq2, y: Fq2) -> Point:
    """Apply the 3-isogeny E2' -> E2 (rational map in x with y scaling)."""
    x_pows = [Fq2.one(), x, x.square(), x.square() * x]
    xn = Fq2.zero()
    for i, k in enumerate(_XNUM):
        xn = xn + k * x_pows[i]
    xd = Fq2.zero()
    for i, k in enumerate(_XDEN):
        xd = xd + k * x_pows[i]
    yn = Fq2.zero()
    for i, k in enumerate(_YNUM):
        yn = yn + k * x_pows[i]
    yd = Fq2.zero()
    for i, k in enumerate(_YDEN):
        yd = yd + k * x_pows[i]
    if xd.is_zero() or yd.is_zero():
        return g2_infinity()
    xo = xn * xd.inv()
    yo = y * yn * yd.inv()
    return Point(xo, yo, Fq2.one(), B2)


def clear_cofactor(p: Point) -> Point:
    return p * H_EFF


def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> Point:
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = iso_map(*sswu_map(u0))
    q1 = iso_map(*sswu_map(u1))
    return clear_cofactor(q0 + q1)
