"""BLS12-381 field tower: Fq -> Fq2 -> Fq6 -> Fq12.

From-scratch pure-Python arithmetic (the framework's correctness oracle for
the TPU limb kernels; capability counterpart of the reference's external
py_ecc dependency, see SURVEY.md §2.2).  Tower construction:

    Fq2  = Fq[u]  / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - XI),  XI = u + 1
    Fq12 = Fq6[w] / (w^2 - v)

Fq elements are plain ints (mod Q); extension elements are slotted classes.
"""
from __future__ import annotations

# field modulus
Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# curve (subgroup) order
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter z (negative): q and r are polynomials in z
BLS_X = -0xD201000000010000


def fq_inv(a: int) -> int:
    return pow(a, Q - 2, Q)


def fq_sqrt(a: int) -> int | None:
    """Square root in Fq (Q ≡ 3 mod 4), or None if a is not a QR."""
    a %= Q
    if a == 0:
        return 0
    s = pow(a, (Q + 1) // 4, Q)
    return s if s * s % Q == a else None


class Fq2:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % Q
        self.c1 = c1 % Q

    @staticmethod
    def zero() -> "Fq2":
        return Fq2(0, 0)

    @staticmethod
    def one() -> "Fq2":
        return Fq2(1, 0)

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o) -> "Fq2":
        if isinstance(o, int):
            return Fq2(self.c0 * o, self.c1 * o)
        a, b, c, d = self.c0, self.c1, o.c0, o.c1
        ac = a * c
        bd = b * d
        return Fq2(ac - bd, (a + b) * (c + d) - ac - bd)

    __rmul__ = __mul__

    def square(self) -> "Fq2":
        a, b = self.c0, self.c1
        return Fq2((a + b) * (a - b), 2 * a * b)

    def mul_by_xi(self) -> "Fq2":
        """Multiply by XI = u + 1:  (a + bu)(1 + u) = (a - b) + (a + b)u."""
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def inv(self) -> "Fq2":
        n = fq_inv(self.c0 * self.c0 + self.c1 * self.c1)
        return Fq2(self.c0 * n, -self.c1 * n)

    def pow(self, e: int) -> "Fq2":
        result = Fq2.one()
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def sqrt(self) -> "Fq2 | None":
        """Square root via the complex method (u^2 = -1), or None."""
        a, b = self.c0, self.c1
        if b == 0:
            s = fq_sqrt(a)
            if s is not None:
                return Fq2(s, 0)
            s = fq_sqrt(-a % Q)
            assert s is not None
            return Fq2(0, s)
        # norm = a^2 + b^2 must be a QR in Fq
        n = fq_sqrt((a * a + b * b) % Q)
        if n is None:
            return None
        inv2 = fq_inv(2)
        t = (a + n) * inv2 % Q
        x = fq_sqrt(t)
        if x is None:
            t = (a - n) * inv2 % Q
            x = fq_sqrt(t)
            if x is None:
                return None
        y = b * inv2 * fq_inv(x) % Q
        cand = Fq2(x, y)
        return cand if cand.square() == self else None

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for GF(q^2): parity of c0, tie-broken by c1."""
        sign_0 = self.c0 % 2
        zero_0 = self.c0 == 0
        sign_1 = self.c1 % 2
        return sign_0 | (zero_0 & sign_1)

    def __repr__(self):
        return f"Fq2(0x{self.c0:x}, 0x{self.c1:x})"


XI = Fq2(1, 1)


class Fq6:
    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0 = c0
        self.c1 = c1
        self.c2 = c2

    @staticmethod
    def zero() -> "Fq6":
        return Fq6(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def one() -> "Fq6":
        return Fq6(Fq2.one(), Fq2.zero(), Fq2.zero())

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, o) -> bool:
        return (isinstance(o, Fq6) and self.c0 == o.c0 and self.c1 == o.c1
                and self.c2 == o.c2)

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a1 * b1
        t2 = a2 * b2
        # Karatsuba-style recombination with v^3 = XI
        c0 = t0 + ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_xi()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_xi()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def mul_by_fq2(self, s: Fq2) -> "Fq6":
        return Fq6(self.c0 * s, self.c1 * s, self.c2 * s)

    def square(self) -> "Fq6":
        return self * self

    def mul_by_v(self) -> "Fq6":
        """Multiply by v: (c0, c1, c2) -> (XI*c2, c0, c1)."""
        return Fq6(self.c2.mul_by_xi(), self.c0, self.c1)

    def inv(self) -> "Fq6":
        a, b, c = self.c0, self.c1, self.c2
        t0 = a.square() - (b * c).mul_by_xi()
        t1 = c.square().mul_by_xi() - a * b
        t2 = b.square() - a * c
        d = (a * t0 + (c * t1 + b * t2).mul_by_xi()).inv()
        return Fq6(t0 * d, t1 * d, t2 * d)

    def __repr__(self):
        return f"Fq6({self.c0!r}, {self.c1!r}, {self.c2!r})"


class Fq12:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0 = c0
        self.c1 = c1

    @staticmethod
    def zero() -> "Fq12":
        return Fq12(Fq6.zero(), Fq6.zero())

    @staticmethod
    def one() -> "Fq12":
        return Fq12(Fq6.one(), Fq6.zero())

    @staticmethod
    def from_fq(x: int) -> "Fq12":
        return Fq12(Fq6(Fq2(x, 0), Fq2.zero(), Fq2.zero()), Fq6.zero())

    def is_one(self) -> bool:
        return self == Fq12.one()

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq12":
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o: "Fq12") -> "Fq12":
        a0, a1 = self.c0, self.c1
        b0, b1 = o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fq12(c0, c1)

    def square(self) -> "Fq12":
        a0, a1 = self.c0, self.c1
        t = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_by_v()) - t - t.mul_by_v()
        return Fq12(c0, t + t)

    def conjugate(self) -> "Fq12":
        """The q^6 Frobenius: negate the w coordinate."""
        return Fq12(self.c0, -self.c1)

    def inv(self) -> "Fq12":
        a, b = self.c0, self.c1
        d = (a.square() - b.square().mul_by_v()).inv()
        return Fq12(a * d, -(b * d))

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inv().pow(-e)
        result = Fq12.one()
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def frobenius(self, power: int = 1) -> "Fq12":
        """x -> x^(q^power) via conjugation + precomputed XI powers.

        Basis {1, v, v^2, w, vw, v^2 w} = w^{0,2,4,1,3,5}; phi(w^k a) =
        conj(a) * XI^(k(q-1)/6) * w^k.
        """
        f = self
        for _ in range(power % 12):
            a0, a1, a2 = f.c0.c0, f.c0.c1, f.c0.c2
            b0, b1, b2 = f.c1.c0, f.c1.c1, f.c1.c2
            f = Fq12(
                Fq6(a0.conjugate(),
                    a1.conjugate() * _FROB_GAMMA[2],
                    a2.conjugate() * _FROB_GAMMA[4]),
                Fq6(b0.conjugate() * _FROB_GAMMA[1],
                    b1.conjugate() * _FROB_GAMMA[3],
                    b2.conjugate() * _FROB_GAMMA[5]))
        return f

    def cyclotomic_square(self) -> "Fq12":
        """Granger-Scott squaring, valid for unitary elements (those in the
        image of the easy final-exponentiation part).  ~3x cheaper than a
        generic square: three Fq4 squarings."""
        z0, z4, z3 = self.c0.c0, self.c0.c1, self.c0.c2
        z2, z1, z5 = self.c1.c0, self.c1.c1, self.c1.c2

        t0, t1 = _fq4_square(z0, z1)
        z0 = t0 - z0
        z0 = z0 + z0 + t0
        z1 = t1 + z1
        z1 = z1 + z1 + t1

        t0, t1 = _fq4_square(z2, z3)
        t2, t3 = _fq4_square(z4, z5)
        z4 = t0 - z4
        z4 = z4 + z4 + t0
        z5 = t1 + z5
        z5 = z5 + z5 + t1

        t0 = t3.mul_by_xi()
        z2 = t0 + z2
        z2 = z2 + z2 + t0
        z3 = t2 - z3
        z3 = z3 + z3 + t2

        return Fq12(Fq6(z0, z4, z3), Fq6(z2, z1, z5))

    def __repr__(self):
        return f"Fq12({self.c0!r}, {self.c1!r})"


def _fq4_square(a: Fq2, b: Fq2) -> tuple[Fq2, Fq2]:
    """Square of a + b*t in Fq4 = Fq2[t]/(t^2 - XI)."""
    t0 = a.square()
    t1 = b.square()
    c0 = t1.mul_by_xi() + t0
    c1 = (a + b).square() - t0 - t1
    return c0, c1


# Frobenius coefficients XI^(k(q-1)/6) for the w^k basis scalings
assert (Q - 1) % 6 == 0
_FROB_GAMMA = [XI.pow(k * (Q - 1) // 6) for k in range(6)]
