"""Whisk proof system: tracker opening proofs and shuffle proofs.

The reference delegates these to the external Rust `curdleproofs` package
(/root/reference/specs/_features/whisk/beacon-chain.md:105-128).  This
module provides a from-scratch, self-contained implementation over our own
BLS12-381 G1 with the same verifier interface:

* Opening proof — a Chaum–Pedersen DLEQ sigma protocol proving knowledge
  of k with  tracker.k_r_G == k * tracker.r_G  and
  k_commitment == k * G  (exactly the relation the spec demands).
  Sound and zero-knowledge; Fiat–Shamir over SHA-256.

* Shuffle proof — a ZERO-KNOWLEDGE shuffle argument over a switching
  network: the permutation is routed through an odd-even transposition
  network of 2x2 switches; each switch's outputs are freshly
  rerandomized, and a CDS OR-composed pair of Chaum–Pedersen DLEQ sigma
  protocols proves "straight OR crossed" without revealing which.  The
  verifier learns only that post is a rerandomized permutation of pre —
  never the permutation itself (computational hiding under DDH in G1;
  honest-verifier ZK made non-interactive by Fiat–Shamir).  Proof size
  is O(n^2) group elements — the minimal-preset ORACLE engine
  (WHISK_VALIDATORS_PER_SHUFFLE=4, ~4.4 KiB).  The mainnet-size engine
  is the polynomial KZG argument in whisk_poly.py (O(n) scalars,
  ~5 KiB at n=124); verify_shuffle dispatches on the proof's format
  tag, so both live behind the one spec-facing verifier.

Proof wire formats are length-prefixed concatenations of compressed G1
points and 32-byte scalars, within the spec's ByteList bounds.
"""
from __future__ import annotations

from .curve import (
    Point, DecodeError, g1_from_bytes, g1_to_bytes, g1_generator,
)
from .fields import R
from ..utils.hash import hash as sha256


def _scalar_to_bytes(x: int) -> bytes:
    return int(x % R).to_bytes(32, "big")


def _bytes_to_scalar(b: bytes) -> int:
    return int.from_bytes(b, "big") % R


def _challenge(*parts: bytes) -> int:
    acc = b"whisk-dleq-v1"
    for part in parts:
        acc += part
    return _bytes_to_scalar(sha256(acc))


# ---------------------------------------------------------------------------
# opening proof (DLEQ)
# ---------------------------------------------------------------------------

OPENING_PROOF_SIZE = 48 + 48 + 32


def prove_opening(tracker_r_G: bytes, k: int, t: int) -> bytes:
    """Prove k: k_r_G = k*r_G and k_commitment = k*G.  `t` is the
    prover's randomness (caller supplies; tests use deterministic t)."""
    r_G = g1_from_bytes(tracker_r_G)
    G = g1_generator()
    a1 = r_G * t
    a2 = G * t
    k_r_G = r_G * k
    k_commitment = G * k
    c = _challenge(tracker_r_G, g1_to_bytes(k_r_G),
                   g1_to_bytes(k_commitment),
                   g1_to_bytes(a1), g1_to_bytes(a2))
    s = (t + c * k) % R
    return g1_to_bytes(a1) + g1_to_bytes(a2) + _scalar_to_bytes(s)


def verify_opening(tracker_r_G: bytes, tracker_k_r_G: bytes,
                   k_commitment: bytes, proof: bytes) -> bool:
    if len(proof) != OPENING_PROOF_SIZE:
        return False
    try:
        r_G = g1_from_bytes(bytes(tracker_r_G))
        k_r_G = g1_from_bytes(bytes(tracker_k_r_G))
        k_comm = g1_from_bytes(bytes(k_commitment))
        a1 = g1_from_bytes(bytes(proof[:48]))
        a2 = g1_from_bytes(bytes(proof[48:96]))
    except DecodeError:
        return False
    s = _bytes_to_scalar(proof[96:128])
    c = _challenge(bytes(tracker_r_G), bytes(tracker_k_r_G),
                   bytes(k_commitment), bytes(proof[:48]),
                   bytes(proof[48:96]))
    G = g1_generator()
    return (r_G * s == a1 + k_r_G * c) and (G * s == a2 + k_comm * c)


# ---------------------------------------------------------------------------
# shuffle proof (zero-knowledge switching-network argument)
# ---------------------------------------------------------------------------
#
# Network topology (public, depends only on n): L = n layers of an
# odd-even transposition network; layer l pairs wires (i, i+1) for
# i = l%2, l%2 + 2, ...  Any permutation of n elements is realizable.
#
# Per switch with input trackers X1, X2 and output trackers Y1, Y2 the
# prover shows, via a CDS OR-proof of two DLEQ conjunctions:
#     [exists a,b: Y1 = a*X1 and Y2 = b*X2]   (straight)
#  or [exists a,b: Y1 = a*X2 and Y2 = b*X1]   (crossed)
# A tracker is a G1 pair (A, B); "Y = w*X" is the two-equation DLEQ
# Ya = w*Xa, Yb = w*Xb proven with one response.  Unswitched wires pass
# through unchanged (topology is public, so this leaks nothing).
#
# Switch proof wire format (544 bytes):
#   8 x 48B commitment points (branch0: C1a C1b C2a C2b, branch1: same)
#   1 x 32B sub-challenge c0 (c1 = c - c0 mod R, c = Fiat-Shamir)
#   4 x 32B responses (branch0: s1 s2, branch1: s1 s2)

_SWITCH_PROOF_SIZE = 8 * 48 + 32 + 4 * 32


def _network_layers(n: int):
    """Switch positions per layer: layer l pairs (i, i+1), i stepping by
    2 from l%2."""
    return [[(i, i + 1) for i in range(l % 2, n - 1, 2)]
            for l in range(n)]


def _route_network(permutation):
    """Switch settings realizing `permutation` (post[i] = pre[perm[i]]).

    Simulate the network in reverse: start from the output arrangement
    and run odd-even transposition sort back to the identity; a
    compare-exchange that swaps becomes a crossed switch when replayed
    forward.  Returns settings[layer] = list of bools (crossed?)."""
    n = len(permutation)
    layers = _network_layers(n)
    arr = list(permutation)
    settings = []
    for swaps in reversed(layers):
        layer_set = []
        for (i, j) in swaps:
            if arr[i] > arr[j]:
                arr[i], arr[j] = arr[j], arr[i]
                layer_set.append(True)
            else:
                layer_set.append(False)
        settings.append(layer_set)
    if arr != list(range(n)):  # n passes always sort; defensive
        raise ValueError("routing failed")
    settings.reverse()
    return settings


class _Rand:
    """Deterministic scalar stream from a seed (prover-side randomness;
    callers supply fresh entropy in production, fixed seeds in tests)."""

    def __init__(self, seed: bytes):
        self._seed = bytes(seed)
        self._ctr = 0

    def scalar(self) -> int:
        while True:
            self._ctr += 1
            v = _bytes_to_scalar(sha256(
                b"whisk-shuffle-rand" + self._seed +
                self._ctr.to_bytes(8, "little")))
            if v != 0:
                return v


def _tracker_bytes(t) -> bytes:
    return bytes(t[0]) + bytes(t[1])


def _dleq_check(X, Y, C1, C2, c, s) -> bool:
    """s*X == C + c*Y componentwise for tracker pairs X, Y."""
    return (X[0] * s == C1 + Y[0] * c) and (X[1] * s == C2 + Y[1] * c)


def _switch_transcript(transcript, X1, X2, Y1, Y2) -> bytes:
    """Bind the switch's inputs AND outputs into its challenge: a
    challenge that omits Y lets a cheating prover pick commitments with
    known coefficients and solve for Y after seeing c (forged outputs
    that are multiples of neither input)."""
    return transcript + b"".join(
        g1_to_bytes(P[0]) + g1_to_bytes(P[1]) for P in (X1, X2, Y1, Y2))


def _prove_switch(X1, X2, Y1, Y2, crossed: bool, a: int, b: int,
                  rand: _Rand, transcript: bytes) -> bytes:
    """OR-proof for one switch.  (a, b) are the rerandomizers with
    Y1 = a*X[cross?2:1], Y2 = b*X[cross?1:2]."""
    transcript = _switch_transcript(transcript, X1, X2, Y1, Y2)
    in_true = (X2, X1) if crossed else (X1, X2)
    in_false = (X1, X2) if crossed else (X2, X1)

    # simulate the false branch: random challenge + responses, derive
    # commitments backwards
    c_false = rand.scalar()
    sf1, sf2 = rand.scalar(), rand.scalar()
    Cf = (in_false[0][0] * sf1 + (-(Y1[0] * c_false)),
          in_false[0][1] * sf1 + (-(Y1[1] * c_false)),
          in_false[1][0] * sf2 + (-(Y2[0] * c_false)),
          in_false[1][1] * sf2 + (-(Y2[1] * c_false)))

    # honest commitments for the true branch
    t1, t2 = rand.scalar(), rand.scalar()
    Ct = (in_true[0][0] * t1, in_true[0][1] * t1,
          in_true[1][0] * t2, in_true[1][1] * t2)

    branch0 = Cf if crossed else Ct
    branch1 = Ct if crossed else Cf
    comm = b"".join(g1_to_bytes(P) for P in branch0 + branch1)
    c = _bytes_to_scalar(sha256(b"whisk-switch-v1" + transcript + comm))
    c_true = (c - c_false) % R
    st1 = (t1 + c_true * a) % R
    st2 = (t2 + c_true * b) % R

    if crossed:
        c0, s01, s02, s11, s12 = c_false, sf1, sf2, st1, st2
    else:
        c0, s01, s02, s11, s12 = c_true, st1, st2, sf1, sf2
    return (comm + _scalar_to_bytes(c0) +
            _scalar_to_bytes(s01) + _scalar_to_bytes(s02) +
            _scalar_to_bytes(s11) + _scalar_to_bytes(s12))


def _verify_switch(X1, X2, Y1, Y2, proof: bytes, transcript: bytes) -> bool:
    if len(proof) != _SWITCH_PROOF_SIZE:
        return False
    transcript = _switch_transcript(transcript, X1, X2, Y1, Y2)
    try:
        C = [g1_from_bytes(bytes(proof[i * 48:(i + 1) * 48]))
             for i in range(8)]
    except DecodeError:
        return False
    off = 8 * 48
    c0 = _bytes_to_scalar(proof[off:off + 32])
    s01 = _bytes_to_scalar(proof[off + 32:off + 64])
    s02 = _bytes_to_scalar(proof[off + 64:off + 96])
    s11 = _bytes_to_scalar(proof[off + 96:off + 128])
    s12 = _bytes_to_scalar(proof[off + 128:off + 160])
    c = _bytes_to_scalar(sha256(b"whisk-switch-v1" + transcript +
                                bytes(proof[:8 * 48])))
    c1 = (c - c0) % R
    # branch 0: straight (Y1 from X1, Y2 from X2)
    if not (_dleq_check(X1, Y1, C[0], C[1], c0, s01) and
            _dleq_check(X2, Y2, C[2], C[3], c0, s02)):
        return False
    # branch 1: crossed (Y1 from X2, Y2 from X1)
    if not (_dleq_check(X2, Y1, C[4], C[5], c1, s11) and
            _dleq_check(X1, Y2, C[6], C[7], c1, s12)):
        return False
    return True


def _decode_trackers(trackers):
    """Decode and reject identity components: a zero DLEQ witness maps a
    tracker to the point at infinity and would still satisfy the sigma
    equations, so infinity must never appear at any network layer (the
    transcript-era verifier's s == 0 check, enforced structurally)."""
    out = []
    for t in trackers:
        a = g1_from_bytes(bytes(t[0]))
        b = g1_from_bytes(bytes(t[1]))
        if a.is_infinity() or b.is_infinity():
            raise DecodeError("identity tracker component")
        out.append((a, b))
    return out


def prove_shuffle(pre_trackers: list, permutation: list,
                  rerandomizers: list, seed: bytes | None = None) -> tuple:
    """Build (post_trackers, proof_bytes) with
    post[i] = rerandomizers[i] * pre[permutation[i]].

    pre_trackers is a list of (r_G_bytes, k_r_G_bytes).  The proof hides
    the permutation: it routes through an odd-even transposition network,
    rerandomizing at every switch, with an OR-proof per switch.

    `seed` drives prover randomness.  Default None = fresh OS entropy —
    the only hiding choice: a recomputable seed lets anyone replay the
    _Rand stream, match each switch's c_false against the proof's c0,
    and read off the permutation.  Pass an explicit seed ONLY for
    deterministic tests, never reusing one across proofs (nonce reuse
    leaks the rerandomizers via s - s' = (c - c')*a)."""
    import os as _os
    n = len(pre_trackers)
    assert sorted(permutation) == list(range(n))
    assert all(r % R != 0 for r in rerandomizers), \
        "zero rerandomizer would map a tracker to infinity"
    if seed is None:
        seed = _os.urandom(32)
    rand = _Rand(seed + b"|" + b"".join(
        bytes(t[0]) for t in pre_trackers))
    if n == 1:
        # no permutation to hide: a single DLEQ proves post = r * pre
        r = rerandomizers[0] % R
        pre_pt = _decode_trackers(pre_trackers)[0]
        post_pt = (pre_pt[0] * r, pre_pt[1] * r)
        post_b = (g1_to_bytes(post_pt[0]), g1_to_bytes(post_pt[1]))
        t = rand.scalar()
        C1, C2 = pre_pt[0] * t, pre_pt[1] * t
        ts = sha256(b"whisk-shuffle-n1" + _tracker_bytes(pre_trackers[0])
                    + _tracker_bytes(post_b))
        c = _bytes_to_scalar(sha256(
            ts + g1_to_bytes(C1) + g1_to_bytes(C2)))
        s = (t + c * r) % R
        proof = (n.to_bytes(4, "little") + g1_to_bytes(C1)
                 + g1_to_bytes(C2) + _scalar_to_bytes(s))
        return [post_b], proof
    layers = _network_layers(n)
    settings = _route_network(permutation)

    # plan per-wire scalars: random everywhere, then fix each wire's
    # *last* touching switch so the path product hits the target
    current = _decode_trackers(pre_trackers)     # tracker points per wire
    acc = [1] * n          # accumulated rerandomization per current wire
    src = list(range(n))   # pre-index currently riding each wire
    target = {permutation[i]: rerandomizers[i] % R for i in range(n)}
    # how many switches remain touching each wire (to know "last touch")
    remaining = [sum(1 for lay in layers for (i, j) in lay
                     if w in (i, j)) for w in range(n)]

    proof_parts = [n.to_bytes(4, "little")]
    statement = sha256(b"whisk-shuffle-stmt" + b"".join(
        _tracker_bytes(t) for t in pre_trackers))
    layer_blobs = []
    switch_proofs = []

    for lidx, lay in enumerate(layers):
        new_current = list(current)
        new_acc = list(acc)
        new_src = list(src)
        for sidx, (i, j) in enumerate(lay):
            crossed = settings[lidx][sidx]
            srcs = (src[j], src[i]) if crossed else (src[i], src[j])
            ins = (current[j], current[i]) if crossed \
                else (current[i], current[j])
            accs = (acc[j], acc[i]) if crossed else (acc[i], acc[j])
            outs, out_acc, scalars = [], [], []
            for w, (s_idx, inp, ac) in enumerate(zip(srcs, ins, accs)):
                remaining_after = remaining[(i, j)[w]] - 1
                if remaining_after == 0 and s_idx in target:
                    # last touch: land exactly on the requested product
                    sc = (target[s_idx] * pow(ac, R - 2, R)) % R
                else:
                    sc = rand.scalar()
                scalars.append(sc)
                outs.append((inp[0] * sc, inp[1] * sc))
                out_acc.append((ac * sc) % R)
            new_current[i], new_current[j] = outs
            new_acc[i], new_acc[j] = out_acc
            new_src[i], new_src[j] = srcs
        for (i, j) in lay:
            remaining[i] -= 1
            remaining[j] -= 1
        # serialize this layer's outputs (the final layer is implicit:
        # the verifier uses post_trackers for it)
        if lidx < len(layers) - 1:
            layer_blobs.append(b"".join(
                g1_to_bytes(p[0]) + g1_to_bytes(p[1])
                for p in new_current))
        # per-switch OR proofs, bound to the statement and position
        for sidx, (i, j) in enumerate(lay):
            crossed = settings[lidx][sidx]
            a_src = src[j] if crossed else src[i]
            # recompute the scalars used (stored implicitly above); we
            # re-derive them from the acc bookkeeping
            # a = out_acc_of_wire_i / acc_of_input_feeding_Y1
            X1, X2 = current[i], current[j]
            Y1, Y2 = new_current[i], new_current[j]
            in1_acc = acc[j] if crossed else acc[i]
            in2_acc = acc[i] if crossed else acc[j]
            a = (new_acc[i] * pow(in1_acc, R - 2, R)) % R
            b = (new_acc[j] * pow(in2_acc, R - 2, R)) % R
            ts = (statement + lidx.to_bytes(4, "little") +
                  sidx.to_bytes(4, "little"))
            switch_proofs.append(_prove_switch(
                X1, X2, Y1, Y2, crossed, a, b, rand, ts))
        current, acc, src = new_current, new_acc, new_src

    post = [(g1_to_bytes(p[0]), g1_to_bytes(p[1])) for p in current]
    # sanity: the network routed every wire to the requested source
    assert src == list(permutation), (src, permutation)
    proof = b"".join(proof_parts) + b"".join(layer_blobs) + \
        b"".join(switch_proofs)
    return post, proof


def verify_shuffle(pre_trackers: list, post_trackers: list,
                   proof: bytes) -> bool:
    """Verify post is a rerandomized permutation of pre.  Zero-knowledge:
    the proof reveals nothing about the permutation.

    Two proof engines behind one verifier: the O(n^2) switching network
    (minimal-preset oracle, below) and the polynomial KZG argument
    (whisk_poly, mainnet n=124 — ~5 KiB), selected by the proof's
    format tag."""
    n = len(pre_trackers)
    if len(post_trackers) != n or n == 0:
        return False
    proof = bytes(proof)
    if len(proof) >= 8 and proof[4:8] == b"POLY":
        from .whisk_poly import verify_shuffle_poly
        return verify_shuffle_poly(pre_trackers, post_trackers, proof)
    if len(proof) < 4 or int.from_bytes(proof[:4], "little") != n:
        return False
    if n == 1:
        if len(proof) != 4 + 48 + 48 + 32:
            return False
        try:
            (pre_pt,) = _decode_trackers(pre_trackers)
            (post_pt,) = _decode_trackers(post_trackers)
            C1 = g1_from_bytes(proof[4:52])
            C2 = g1_from_bytes(proof[52:100])
        except DecodeError:
            return False
        s = _bytes_to_scalar(proof[100:132])
        ts = sha256(b"whisk-shuffle-n1"
                    + _tracker_bytes(pre_trackers[0])
                    + _tracker_bytes(post_trackers[0]))
        c = _bytes_to_scalar(sha256(
            ts + g1_to_bytes(C1) + g1_to_bytes(C2)))
        return (pre_pt[0] * s == C1 + post_pt[0] * c
                and pre_pt[1] * s == C2 + post_pt[1] * c)
    layers = _network_layers(n)
    n_switches = sum(len(lay) for lay in layers)
    expect = 4 + (len(layers) - 1) * n * 96 + \
        n_switches * _SWITCH_PROOF_SIZE
    if len(proof) != expect:
        return False

    off = 4
    try:
        layer_vals = []
        for _ in range(len(layers) - 1):
            lay = []
            for _w in range(n):
                a = g1_from_bytes(proof[off:off + 48])
                b = g1_from_bytes(proof[off + 48:off + 96])
                if a.is_infinity() or b.is_infinity():
                    return False  # zero-witness escape hatch (see
                    # _decode_trackers) — identity never legal mid-network
                lay.append((a, b))
                off += 96
            layer_vals.append(lay)
        current = _decode_trackers(pre_trackers)
        final = _decode_trackers(post_trackers)
    except DecodeError:
        return False
    layer_vals.append(final)

    statement = sha256(b"whisk-shuffle-stmt" + b"".join(
        _tracker_bytes(t) for t in pre_trackers))
    for lidx, lay in enumerate(layers):
        nxt = layer_vals[lidx]
        switched = set()
        for (i, j) in lay:
            switched.update((i, j))
        # pass-through wires must be unchanged
        for w in range(n):
            if w not in switched:
                if not (current[w][0] == nxt[w][0] and
                        current[w][1] == nxt[w][1]):
                    return False
        for sidx, (i, j) in enumerate(lay):
            ts = (statement + lidx.to_bytes(4, "little") +
                  sidx.to_bytes(4, "little"))
            sw = proof[off:off + _SWITCH_PROOF_SIZE]
            off += _SWITCH_PROOF_SIZE
            if not _verify_switch(current[i], current[j],
                                  nxt[i], nxt[j], sw, ts):
                return False
        current = nxt
    return True
