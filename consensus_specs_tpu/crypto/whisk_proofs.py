"""Whisk proof system: tracker opening proofs and shuffle proofs.

The reference delegates these to the external Rust `curdleproofs` package
(/root/reference/specs/_features/whisk/beacon-chain.md:105-128).  This
module provides a from-scratch, self-contained implementation over our own
BLS12-381 G1 with the same verifier interface:

* Opening proof — a Chaum–Pedersen DLEQ sigma protocol proving knowledge
  of k with  tracker.k_r_G == k * tracker.r_G  and
  k_commitment == k * G  (exactly the relation the spec demands).
  Sound and zero-knowledge; Fiat–Shamir over SHA-256.

* Shuffle proof — a permutation-rerandomization transcript: the prover
  reveals the permutation and per-element rerandomizers, the verifier
  checks  post[i] == r_i * pre[perm[i]]  componentwise.  This verifies the
  *shuffle property* the spec requires but is NOT zero-knowledge (the
  permutation is public); swapping in a curdleproofs-class ZK argument
  behind the same interface is planned kernel work for a later round.

Proof wire formats are length-prefixed concatenations of compressed G1
points and 32-byte scalars, within the spec's ByteList bounds.
"""
from __future__ import annotations

from .curve import (
    Point, DecodeError, g1_from_bytes, g1_to_bytes, g1_generator,
)
from .fields import R
from ..utils.hash import hash as sha256


def _scalar_to_bytes(x: int) -> bytes:
    return int(x % R).to_bytes(32, "big")


def _bytes_to_scalar(b: bytes) -> int:
    return int.from_bytes(b, "big") % R


def _challenge(*parts: bytes) -> int:
    acc = b"whisk-dleq-v1"
    for part in parts:
        acc += part
    return _bytes_to_scalar(sha256(acc))


# ---------------------------------------------------------------------------
# opening proof (DLEQ)
# ---------------------------------------------------------------------------

OPENING_PROOF_SIZE = 48 + 48 + 32


def prove_opening(tracker_r_G: bytes, k: int, t: int) -> bytes:
    """Prove k: k_r_G = k*r_G and k_commitment = k*G.  `t` is the
    prover's randomness (caller supplies; tests use deterministic t)."""
    r_G = g1_from_bytes(tracker_r_G)
    G = g1_generator()
    a1 = r_G * t
    a2 = G * t
    k_r_G = r_G * k
    k_commitment = G * k
    c = _challenge(tracker_r_G, g1_to_bytes(k_r_G),
                   g1_to_bytes(k_commitment),
                   g1_to_bytes(a1), g1_to_bytes(a2))
    s = (t + c * k) % R
    return g1_to_bytes(a1) + g1_to_bytes(a2) + _scalar_to_bytes(s)


def verify_opening(tracker_r_G: bytes, tracker_k_r_G: bytes,
                   k_commitment: bytes, proof: bytes) -> bool:
    if len(proof) != OPENING_PROOF_SIZE:
        return False
    try:
        r_G = g1_from_bytes(bytes(tracker_r_G))
        k_r_G = g1_from_bytes(bytes(tracker_k_r_G))
        k_comm = g1_from_bytes(bytes(k_commitment))
        a1 = g1_from_bytes(bytes(proof[:48]))
        a2 = g1_from_bytes(bytes(proof[48:96]))
    except DecodeError:
        return False
    s = _bytes_to_scalar(proof[96:128])
    c = _challenge(bytes(tracker_r_G), bytes(tracker_k_r_G),
                   bytes(k_commitment), bytes(proof[:48]),
                   bytes(proof[48:96]))
    G = g1_generator()
    return (r_G * s == a1 + k_r_G * c) and (G * s == a2 + k_comm * c)


# ---------------------------------------------------------------------------
# shuffle proof (permutation + rerandomization transcript)
# ---------------------------------------------------------------------------

def prove_shuffle(pre_trackers: list, permutation: list,
                  rerandomizers: list) -> tuple:
    """Build (post_trackers, proof_bytes).  pre_trackers is a list of
    (r_G_bytes, k_r_G_bytes); post[i] = rerandomizers[i] *
    pre[permutation[i]]."""
    n = len(pre_trackers)
    assert sorted(permutation) == list(range(n))
    post = []
    for i in range(n):
        r_G = g1_from_bytes(pre_trackers[permutation[i]][0])
        k_r_G = g1_from_bytes(pre_trackers[permutation[i]][1])
        s = rerandomizers[i] % R
        post.append((g1_to_bytes(r_G * s), g1_to_bytes(k_r_G * s)))
    proof = n.to_bytes(4, "little")
    for i in range(n):
        proof += permutation[i].to_bytes(4, "little")
        proof += _scalar_to_bytes(rerandomizers[i])
    return post, proof


def verify_shuffle(pre_trackers: list, post_trackers: list,
                   proof: bytes) -> bool:
    """Check post is a rerandomized permutation of pre per the
    transcript."""
    n = len(pre_trackers)
    if len(post_trackers) != n:
        return False
    if len(proof) < 4 or int.from_bytes(bytes(proof[:4]), "little") != n:
        return False
    if len(proof) != 4 + n * 36:
        return False
    perm, scalars = [], []
    off = 4
    for _ in range(n):
        perm.append(int.from_bytes(bytes(proof[off:off + 4]), "little"))
        scalars.append(_bytes_to_scalar(bytes(proof[off + 4:off + 36])))
        off += 36
    if sorted(perm) != list(range(n)):
        return False
    try:
        for i in range(n):
            pre_r = g1_from_bytes(bytes(pre_trackers[perm[i]][0]))
            pre_kr = g1_from_bytes(bytes(pre_trackers[perm[i]][1]))
            s = scalars[i]
            if s == 0:
                return False
            if g1_to_bytes(pre_r * s) != bytes(post_trackers[i][0]):
                return False
            if g1_to_bytes(pre_kr * s) != bytes(post_trackers[i][1]):
                return False
    except DecodeError:
        return False
    return True
