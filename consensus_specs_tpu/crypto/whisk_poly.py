"""Polynomial (KZG-backed) whisk shuffle argument — the mainnet-size
engine for the curdleproofs slot.

Statement: ``post[i] = k * pre[sigma(i)]`` for a hidden permutation
``sigma`` and hidden uniform rerandomizer ``k`` — the shuffle relation
of the reference's curdleproofs dependency
(specs/_features/whisk/beacon-chain.md:105-128).  The switching-network
argument in whisk_proofs.py is O(n^2) and tops out at the minimal
preset; this argument is O(n) scalars + O(1) group elements (~5 KiB at
mainnet's WHISK_VALIDATORS_PER_SHUFFLE=124, well inside
WHISK_MAX_SHUFFLE_PROOF_SIZE = 2**15).

Construction (original composition over the repo's own KZG/pairing
stack; not curdleproofs wire-compatible — same capability slot):

1. Pair compression: FS scalar z folds each tracker pair to one point
   m_i = R_i + z*S_i (pre), n_i = T_i + z*U_i (post); arrays pad to the
   radix-2 width with m_i = G, n_i = K := k*G.
2. Permutation commitment FIRST: P_a commits a(X) with a_i = sigma(i)
   over the domain (Lagrange-basis KZG = Pedersen vector commitment,
   blinded by Z_H).  Only then is the challenge c drawn, e_i = c^i.
3. B commits b(X) with b_i = e_{sigma(i)}.  A PLONK-style grand
   product with FS challenges beta, gamma proves the pairs (b_i, a_i)
   are a permutation of (e_i, i): the running product of
   (b + beta*a + gamma)/(e + beta*id + gamma) closes at 1.  Quotient
   poly + KZG openings at an FS point zeta make it succinct.
4. MSM link: a Schnorr vector-opening proves N = sum b_i * n_i against
   the SAME commitment B (masked reply vector, so nothing about b
   leaks); a Chaum-Pedersen DLEQ proves N = k*M and K = k*G for the
   publicly computable M = sum e_i * m_i.  With sigma pinned before c,
   Schwartz-Zippel over the c-polynomial forces n_i = k*m_{sigma(i)}
   coordinate-wise.

Zero-knowledge: a, b, Z carry Z_H-multiple blinders (their domain
values are untouched), the vector reply is one-time-pad masked, and K,
N reveal only DDH-hard images of k.
"""
from __future__ import annotations

import os as _os

from ..utils.hash import hash as sha256
from .curve import (
    DecodeError, Point, g1_from_bytes, g1_generator, g1_infinity,
    g1_to_bytes, msm,
)
from .fields import R

# domain/width bookkeeping -------------------------------------------------

def _root_of_unity(order: int) -> int:
    from ..utils.kzg_setup_gen import root_of_unity
    return root_of_unity(order)


def _width_for(n: int) -> int:
    w = 8
    while w < n:
        w <<= 1
    return w


# field polynomial helpers (coefficient form, little-endian) ---------------

def _poly_eval(coeffs, x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % R
    return acc


def _poly_mul(a, b):
    out = [0] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai:
            for j, bj in enumerate(b):
                out[i + j] = (out[i + j] + ai * bj) % R
    return out


def _poly_add(a, b):
    n = max(len(a), len(b))
    return [((a[i] if i < len(a) else 0)
             + (b[i] if i < len(b) else 0)) % R for i in range(n)]


def _poly_scale(a, s: int):
    return [c * s % R for c in a]


def _divide_by_vanishing(coeffs, w: int):
    """Exact division by Z_H = X^w - 1; raises if not divisible."""
    c = list(coeffs)
    q = [0] * max(len(c) - w, 0)
    for i in range(len(c) - 1, w - 1, -1):
        q[i - w] = (q[i - w] + c[i]) % R
        c[i - w] = (c[i - w] + c[i]) % R
        c[i] = 0
    if any(x % R for x in c[:w]):
        raise ValueError("quotient remainder nonzero")
    return q


def _divide_linear(coeffs, zeta: int):
    """(f(X) - f(zeta)) / (X - zeta) via synthetic division."""
    q = [0] * (len(coeffs) - 1)
    acc = 0
    for i in range(len(coeffs) - 1, 0, -1):
        acc = (acc * zeta + coeffs[i]) % R
        q[i - 1] = acc
    return q


def _ifft(evals, w: int, omega: int):
    """Domain evaluations -> coefficients (recursive radix-2)."""
    roots = [pow(omega, i, R) for i in range(w)]
    from .kzg_sampling import fft_field
    return [x % R for x in fft_field([e % R for e in evals], roots,
                                     inv=True)]


# CRS ----------------------------------------------------------------------

class ShuffleCRS:
    """Powers-of-tau slice for one domain width: monomial G1 points up
    to degree w+3, the Lagrange basis over the domain, the Z_H blinding
    bases, and [1, tau] in G2 for the pairing checks."""

    def __init__(self, width: int, monomial: list, g2_points: list):
        assert len(monomial) >= width + 4
        self.width = width
        self.omega = _root_of_unity(width)
        self.monomial = monomial
        from ..utils.kzg_setup_gen import monomial_to_lagrange
        self.lagrange = monomial_to_lagrange(monomial[:width])
        g = monomial[0]
        # Z_H(tau)G and X*Z_H(tau)G, X^2*Z_H(tau)G
        self.zh = [monomial[width + i] + (-monomial[i])
                   for i in range(3)]
        self.g2 = g2_points[0]
        self.tau_g2 = g2_points[1]
        self.g = g

    @classmethod
    def from_setup(cls, width: int, setup: dict | None = None):
        """Build from a trusted-setup dict (default: the repo's 4096
        ceremony file, the CURDLEPROOFS_CRS slot)."""
        from .curve import g2_from_bytes
        if setup is None:
            import json
            import os
            path = os.path.join(os.path.dirname(__file__), "..",
                                "config", "trusted_setups",
                                "trusted_setup_4096.json")
            with open(path) as f:
                setup = json.load(f)
        mono = [g1_from_bytes(bytes.fromhex(h[2:]))
                for h in setup["g1_monomial"][:width + 4]]
        g2s = [g2_from_bytes(bytes.fromhex(h[2:]))
               for h in setup["g2_monomial"][:2]]
        return cls(width, mono, g2s)


_CRS_CACHE: dict = {}


def get_crs(width: int) -> ShuffleCRS:
    crs = _CRS_CACHE.get(width)
    if crs is None:
        crs = ShuffleCRS.from_setup(width)
        _CRS_CACHE[width] = crs
    return crs


# transcript ---------------------------------------------------------------

class _Transcript:
    def __init__(self, label: bytes):
        self.state = sha256(b"whisk-poly-v1|" + label)

    def absorb(self, *parts: bytes) -> None:
        acc = self.state
        for p in parts:
            acc += p
        self.state = sha256(acc)

    def challenge(self, label: bytes) -> int:
        out = int.from_bytes(sha256(self.state + label), "big") % R
        self.absorb(b"chal|" + label)
        return out


class _Rand:
    """Deterministic prover randomness (seeded for tests)."""

    def __init__(self, seed: bytes):
        self._state = sha256(b"whisk-poly-rand|" + seed)
        self._n = 0

    def scalar(self) -> int:
        self._n += 1
        out = int.from_bytes(
            sha256(self._state + self._n.to_bytes(8, "little")),
            "big") % R
        return out or 1


# core ---------------------------------------------------------------------

def _compress_pairs(trackers, z: int):
    pts = []
    for r_g, k_r_g in trackers:
        a = g1_from_bytes(bytes(r_g))
        b = g1_from_bytes(bytes(k_r_g))
        pts.append(a + b * z)
    return pts


def _commit(crs: ShuffleCRS, evals, blinders):
    """Commit domain evaluations + Z_H-multiple blinding coefficients:
    C = sum evals_i * L_i + sum blinders_j * (X^j Z_H)(tau) G."""
    points = list(crs.lagrange) + list(crs.zh[:len(blinders)])
    scalars = list(evals) + list(blinders)
    return msm(points, scalars)


def _blinded_coeffs(evals, blinders, w: int, omega: int):
    """Coefficient form of the blinded polynomial."""
    coeffs = _ifft(evals, w, omega)
    # + (sum blinders_j X^j) * (X^w - 1)
    bl = list(blinders)
    ext = [0] * (w + len(bl))
    for j, b in enumerate(bl):
        ext[w + j] = (ext[w + j] + b) % R
        ext[j] = (ext[j] - b) % R
    return _poly_add(coeffs, ext)


def _lagrange_0_at(zeta: int, w: int) -> int:
    """L_0(zeta) = (zeta^w - 1) / (w * (zeta - 1))."""
    num = (pow(zeta, w, R) - 1) % R
    den = w * (zeta - 1) % R
    return num * pow(den, R - 2, R) % R


def prove_shuffle_poly(pre_trackers: list, permutation: list, k: int,
                       seed: bytes | None = None) -> tuple:
    """Build (post_trackers, proof) with post[i] = k * pre[sigma(i)]."""
    n = len(pre_trackers)
    assert sorted(permutation) == list(range(n))
    k = k % R
    assert k != 0
    if seed is None:
        seed = _os.urandom(32)

    pre_pts = [(g1_from_bytes(bytes(a)), g1_from_bytes(bytes(b)))
               for a, b in pre_trackers]
    post_pts = [(pre_pts[permutation[i]][0] * k,
                 pre_pts[permutation[i]][1] * k) for i in range(n)]
    post_trackers = [(g1_to_bytes(a), g1_to_bytes(b))
                     for a, b in post_pts]

    # nonce derivation binds the WHOLE statement + witness: reusing a
    # seed across different (permutation, k, post) must still yield
    # fresh blinders/masks, or replies across proofs leak k and b_vec
    rand = _Rand(
        seed + b"|" + b"".join(
            bytes(t[0]) + bytes(t[1]) for t in pre_trackers)
        + b"|" + b"".join(a + b for a, b in post_trackers)
        + b"|" + b",".join(str(i).encode() for i in permutation)
        + b"|" + int(k).to_bytes(32, "big"))

    w = _width_for(n)
    crs = get_crs(w)
    omega = crs.omega
    g = crs.g

    tr = _Transcript(b"shuffle")
    tr.absorb(n.to_bytes(4, "little"), w.to_bytes(4, "little"))
    for t in pre_trackers:
        tr.absorb(bytes(t[0]), bytes(t[1]))
    for t in post_trackers:
        tr.absorb(bytes(t[0]), bytes(t[1]))

    z = tr.challenge(b"z")
    m = _compress_pairs(pre_trackers, z)
    npts = _compress_pairs(post_trackers, z)
    K = g * k
    m += [g] * (w - n)
    npts += [K] * (w - n)
    tr.absorb(g1_to_bytes(K))

    # permutation commitment BEFORE the vector challenge c
    sigma = list(permutation) + list(range(n, w))
    rho_a = rand.scalar()
    P_a = _commit(crs, sigma, [rho_a])
    tr.absorb(g1_to_bytes(P_a))

    c = tr.challenge(b"c")
    e = [pow(c, i, R) for i in range(w)]
    b_vec = [e[sigma[i]] for i in range(w)]
    rho_b = rand.scalar()
    B = _commit(crs, b_vec, [rho_b])
    tr.absorb(g1_to_bytes(B))

    beta = tr.challenge(b"beta")
    gamma = tr.challenge(b"gamma")

    # grand product evaluations
    zv = [1] * w
    for i in range(w - 1):
        num = (e[i] + beta * i + gamma) % R
        den = (b_vec[i] + beta * sigma[i] + gamma) % R
        zv[i + 1] = zv[i] * num % R * pow(den, R - 2, R) % R
    rho_z = [rand.scalar(), rand.scalar(), rand.scalar()]
    ZC = _commit(crs, zv, rho_z)
    tr.absorb(g1_to_bytes(ZC))

    alpha = tr.challenge(b"alpha")

    # quotient polynomial
    a_hat = _blinded_coeffs(sigma, [rho_a], w, omega)
    b_hat = _blinded_coeffs(b_vec, [rho_b], w, omega)
    z_hat = _blinded_coeffs(zv, rho_z, w, omega)
    e_poly = _ifft(e, w, omega)
    id_poly = _ifft(list(range(w)), w, omega)
    z_shift = [z_hat[i] * pow(omega, i, R) % R
               for i in range(len(z_hat))]           # Z(omega X)
    d_poly = _poly_add(_poly_add(b_hat, _poly_scale(a_hat, beta)),
                       [gamma])
    e_side = _poly_add(_poly_add(e_poly, _poly_scale(id_poly, beta)),
                       [gamma])
    c2 = _poly_add(_poly_mul(z_shift, d_poly),
                   _poly_scale(_poly_mul(z_hat, e_side), R - 1))
    # C1 = L_0(X) * (Z(X) - 1); L_0 evals = [1, 0, ...]
    l0 = _ifft([1] + [0] * (w - 1), w, omega)
    c1 = _poly_mul(l0, _poly_add(z_hat, [R - 1]))
    combined = _poly_add(_poly_scale(c1, alpha), c2)
    q_poly = _divide_by_vanishing(combined, w)
    QC = msm(crs.monomial[:len(q_poly)], q_poly)
    tr.absorb(g1_to_bytes(QC))

    zeta = tr.challenge(b"zeta")
    a_z = _poly_eval(a_hat, zeta)
    b_z = _poly_eval(b_hat, zeta)
    zz = _poly_eval(z_hat, zeta)
    zwz = _poly_eval(z_hat, omega * zeta % R)
    tr.absorb(*[int(v).to_bytes(32, "big")
                for v in (a_z, b_z, zz, zwz)])

    # batched opening at zeta for [a, b, Z, Q] with challenge nu
    nu = tr.challenge(b"nu")
    q_zeta = _poly_eval(q_poly, zeta)
    agg = list(a_hat)
    for p, scale in ((b_hat, nu), (z_hat, nu * nu % R),
                     (q_poly, pow(nu, 3, R))):
        agg = _poly_add(agg, _poly_scale(p, scale))
    agg_val = (a_z + nu * b_z + nu * nu % R * zz
               + pow(nu, 3, R) * q_zeta) % R
    agg[0] = (agg[0] - agg_val) % R
    w1_poly = _divide_linear(agg, zeta)
    W1 = msm(crs.monomial[:len(w1_poly)], w1_poly)
    zh2 = list(z_hat)
    zh2[0] = (zh2[0] - zwz) % R
    w2_poly = _divide_linear(zh2, omega * zeta % R)
    W2 = msm(crs.monomial[:len(w2_poly)], w2_poly)
    tr.absorb(g1_to_bytes(W1), g1_to_bytes(W2))
    _ = tr.challenge(b"batch")   # verifier's pairing-batching scalar

    # MSM link: N = sum b_i n_i; Schnorr vector opening against B
    N = msm(npts, b_vec)
    a_mask = [rand.scalar() for _ in range(w)]
    s_mask = rand.scalar()
    A_rand = _commit(crs, a_mask, [s_mask])
    E = msm(npts, a_mask)
    tr.absorb(g1_to_bytes(N), g1_to_bytes(A_rand), g1_to_bytes(E))
    x = tr.challenge(b"x")
    z_vec = [(x * b_vec[i] + a_mask[i]) % R for i in range(w)]
    t_resp = (x * rho_b + s_mask) % R

    # DLEQ: log_G K == log_M N (the uniform rerandomizer k)
    M = msm(m, e)
    r_dleq = rand.scalar()
    C1p = g * r_dleq
    C2p = M * r_dleq
    tr.absorb(g1_to_bytes(C1p), g1_to_bytes(C2p))
    ch = tr.challenge(b"dleq")
    s_dleq = (r_dleq + ch * k) % R

    proof = b"".join([
        n.to_bytes(4, "little"), b"POLY",
        g1_to_bytes(K), g1_to_bytes(P_a), g1_to_bytes(B),
        g1_to_bytes(ZC), g1_to_bytes(QC),
        g1_to_bytes(W1), g1_to_bytes(W2),
        int(a_z).to_bytes(32, "big"), int(b_z).to_bytes(32, "big"),
        int(zz).to_bytes(32, "big"), int(zwz).to_bytes(32, "big"),
        g1_to_bytes(N), g1_to_bytes(A_rand), g1_to_bytes(E),
        b"".join(int(v).to_bytes(32, "big") for v in z_vec),
        int(t_resp).to_bytes(32, "big"),
        g1_to_bytes(C1p), g1_to_bytes(C2p),
        int(s_dleq).to_bytes(32, "big"),
    ])
    return post_trackers, proof


def _scalar(b: bytes) -> int:
    """Canonical scalar decode: rejecting >= R makes the wire format
    non-malleable (value+R would re-encode the same scalar in 32
    bytes, changing the block root of an embedded proof)."""
    v = int.from_bytes(b, "big")
    if v >= R:
        raise DecodeError("non-canonical scalar")
    return v


def verify_shuffle_poly(pre_trackers: list, post_trackers: list,
                        proof: bytes) -> bool:
    from .pairing import pairing_check

    n = len(pre_trackers)
    if len(post_trackers) != n or n == 0:
        return False
    proof = bytes(proof)
    if len(proof) < 8 or proof[4:8] != b"POLY":
        return False
    if int.from_bytes(proof[:4], "little") != n:
        return False
    w = _width_for(n)
    crs = get_crs(w)
    omega = crs.omega
    g = crs.g

    expect = 8 + 48 * 7 + 32 * 4 + 48 * 3 + 32 * w + 32 + 48 * 2 + 32
    if len(proof) != expect:
        return False
    off = 8

    def point():
        nonlocal off
        p = g1_from_bytes(proof[off:off + 48])
        off += 48
        return p

    def scalar():
        nonlocal off
        v = _scalar(proof[off:off + 32])
        off += 32
        return v

    try:
        K, P_a, B, ZC, QC, W1, W2 = (point() for _ in range(7))
        a_z, b_z, zz, zwz = (scalar() for _ in range(4))
        N, A_rand, E = (point() for _ in range(3))
        z_vec = [scalar() for _ in range(w)]
        t_resp = scalar()
        C1p, C2p = point(), point()
        s_dleq = scalar()
    except DecodeError:
        return False
    if K == g1_infinity():
        # k = 0 satisfies the relation trivially (all post trackers at
        # infinity) — forbidden, like the prover's own k != 0 gate
        return False

    tr = _Transcript(b"shuffle")
    tr.absorb(n.to_bytes(4, "little"), w.to_bytes(4, "little"))
    for t in pre_trackers:
        tr.absorb(bytes(t[0]), bytes(t[1]))
    for t in post_trackers:
        tr.absorb(bytes(t[0]), bytes(t[1]))
    z = tr.challenge(b"z")
    try:
        m = _compress_pairs(pre_trackers, z)
        npts = _compress_pairs(post_trackers, z)
    except DecodeError:
        return False
    m += [g] * (w - n)
    npts += [K] * (w - n)
    tr.absorb(g1_to_bytes(K))
    tr.absorb(g1_to_bytes(P_a))
    c = tr.challenge(b"c")
    e = [pow(c, i, R) for i in range(w)]
    tr.absorb(g1_to_bytes(B))
    beta = tr.challenge(b"beta")
    gamma = tr.challenge(b"gamma")
    tr.absorb(g1_to_bytes(ZC))
    alpha = tr.challenge(b"alpha")
    tr.absorb(g1_to_bytes(QC))
    zeta = tr.challenge(b"zeta")
    tr.absorb(*[int(v).to_bytes(32, "big")
                for v in (a_z, b_z, zz, zwz)])
    nu = tr.challenge(b"nu")

    # quotient evaluation implied by the constraint system
    zh_zeta = (pow(zeta, w, R) - 1) % R
    if zh_zeta == 0:
        return False
    e_zeta = _poly_eval(_ifft(e, w, omega), zeta)
    id_zeta = _poly_eval(_ifft(list(range(w)), w, omega), zeta)
    l0_zeta = _lagrange_0_at(zeta, w)
    d_zeta = (b_z + beta * a_z + gamma) % R
    e_side_zeta = (e_zeta + beta * id_zeta + gamma) % R
    c2_zeta = (zwz * d_zeta - zz * e_side_zeta) % R
    c1_zeta = l0_zeta * (zz - 1) % R
    q_zeta = (alpha * c1_zeta + c2_zeta) % R * pow(
        zh_zeta, R - 2, R) % R

    # batched KZG check at zeta: agg = P_a + nu B + nu^2 ZC + nu^3 QC
    agg_c = P_a + B * nu + ZC * (nu * nu % R) + QC * pow(nu, 3, R)
    agg_v = (a_z + nu * b_z + nu * nu % R * zz
             + pow(nu, 3, R) * q_zeta) % R
    tr.absorb(g1_to_bytes(W1), g1_to_bytes(W2))
    # the two opening equations e(C_i - v_i G + s_i W_i, G2) ==
    # e(W_i, tau G2) fold into ONE pairing_check with a
    # transcript-random split scalar (drawn after W1/W2 are absorbed)
    rho = tr.challenge(b"batch")
    lhs1 = agg_c + (-(g * agg_v)) + W1 * zeta
    lhs2 = ZC + (-(g * zwz)) + W2 * (omega * zeta % R)
    if not pairing_check([(lhs1 + lhs2 * rho, -crs.g2),
                          (W1 + W2 * rho, crs.tau_g2)]):
        return False

    # Schnorr vector opening: ties N to the committed b
    tr.absorb(g1_to_bytes(N), g1_to_bytes(A_rand), g1_to_bytes(E))
    x = tr.challenge(b"x")
    lhs = msm(list(crs.lagrange) + [crs.zh[0]], z_vec + [t_resp])
    if lhs != B * x + A_rand:
        return False
    if msm(npts, z_vec) != N * x + E:
        return False

    # DLEQ: N = k*M, K = k*G
    M = msm(m, e)
    tr.absorb(g1_to_bytes(C1p), g1_to_bytes(C2p))
    ch = tr.challenge(b"dleq")
    if g * s_dleq != C1p + K * ch:
        return False
    if M * s_dleq != C2p + N * ch:
        return False
    return True
