"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2).

Jacobian-coordinate arithmetic plus the ZCash compressed serialization used
by the consensus spec (48-byte G1 / 96-byte G2 with compression, infinity
and sign flags in the top three bits).  From scratch; capability counterpart
of the reference's py_arkworks/milagro bindings (SURVEY.md §2.2).

Both groups share one generic Jacobian implementation; Fq is adapted to the
Fq2-style interface by the Fq1 wrapper.
"""
from __future__ import annotations

from .fields import Q, R, Fq2, fq_inv, fq_sqrt


class Fq1:
    """Adapter giving plain-int Fq elements the extension-field interface."""
    __slots__ = ("v",)

    def __init__(self, v: int):
        self.v = v % Q

    @staticmethod
    def zero():
        return Fq1(0)

    @staticmethod
    def one():
        return Fq1(1)

    def is_zero(self):
        return self.v == 0

    def __eq__(self, o):
        return isinstance(o, Fq1) and self.v == o.v

    def __hash__(self):
        return hash(self.v)

    def __add__(self, o):
        return Fq1(self.v + o.v)

    def __sub__(self, o):
        return Fq1(self.v - o.v)

    def __neg__(self):
        return Fq1(-self.v)

    def __mul__(self, o):
        if isinstance(o, int):
            return Fq1(self.v * o)
        return Fq1(self.v * o.v)

    __rmul__ = __mul__

    def square(self):
        return Fq1(self.v * self.v)

    def inv(self):
        return Fq1(fq_inv(self.v))

    def sqrt(self):
        s = fq_sqrt(self.v)
        return None if s is None else Fq1(s)

    def __repr__(self):
        return f"Fq1(0x{self.v:x})"


# curve constants:  E1: y^2 = x^3 + 4      over Fq
#                   E2: y^2 = x^3 + 4(u+1) over Fq2
B1 = Fq1(4)
B2 = Fq2(4, 4)

# generators (standard BLS12-381 generators)
G1_X = Fq1(0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB)
G1_Y = Fq1(0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1)
G2_X = Fq2(0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
           0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E)
G2_Y = Fq2(0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
           0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE)


class Point:
    """Jacobian point on y^2 = x^3 + b; infinity is Z == 0."""
    __slots__ = ("x", "y", "z", "b")

    def __init__(self, x, y, z, b):
        self.x = x
        self.y = y
        self.z = z
        self.b = b

    @staticmethod
    def infinity(b):
        f = type(b)
        return Point(f.one(), f.one(), f.zero(), b)

    def is_infinity(self) -> bool:
        return self.z.is_zero()

    def affine(self):
        """Return (x, y) field elements, or None for infinity."""
        if self.is_infinity():
            return None
        zinv = self.z.inv()
        zinv2 = zinv.square()
        return (self.x * zinv2, self.y * zinv2 * zinv)

    def on_curve(self) -> bool:
        if self.is_infinity():
            return True
        x, y = self.affine()
        return y.square() == x.square() * x + self.b

    def __eq__(self, o) -> bool:
        if not isinstance(o, Point):
            return NotImplemented
        if self.is_infinity() or o.is_infinity():
            return self.is_infinity() and o.is_infinity()
        # cross-multiplied comparison avoids inversions
        z1s, z2s = self.z.square(), o.z.square()
        if self.x * z2s != o.x * z1s:
            return False
        return self.y * z2s * o.z == o.y * z1s * self.z

    def double(self) -> "Point":
        if self.is_infinity() or self.y.is_zero():
            return Point.infinity(self.b)
        x, y, z = self.x, self.y, self.z
        a = x.square()
        bb = y.square()
        c = bb.square()
        d = (x + bb).square() - a - c
        d = d + d
        e = a + a + a
        f = e.square()
        x3 = f - d - d
        y3 = e * (d - x3) - (c + c + c + c + c + c + c + c)
        z3 = (y * z)
        z3 = z3 + z3
        return Point(x3, y3, z3, self.b)

    def __add__(self, o: "Point") -> "Point":
        if self.is_infinity():
            return o
        if o.is_infinity():
            return self
        z1z1 = self.z.square()
        z2z2 = o.z.square()
        u1 = self.x * z2z2
        u2 = o.x * z1z1
        s1 = self.y * o.z * z2z2
        s2 = o.y * self.z * z1z1
        if u1 == u2:
            if s1 == s2:
                return self.double()
            return Point.infinity(self.b)
        h = u2 - u1
        rr = s2 - s1
        h2 = h.square()
        h3 = h * h2
        u1h2 = u1 * h2
        x3 = rr.square() - h3 - u1h2 - u1h2
        y3 = rr * (u1h2 - x3) - s1 * h3
        z3 = self.z * o.z * h
        return Point(x3, y3, z3, self.b)

    def __neg__(self) -> "Point":
        return Point(self.x, -self.y, self.z, self.b)

    def __sub__(self, o: "Point") -> "Point":
        return self + (-o)

    def __mul__(self, k: int) -> "Point":
        k = int(k)
        if k < 0:
            return (-self) * (-k)
        result = Point.infinity(self.b)
        addend = self
        while k:
            if k & 1:
                result = result + addend
            addend = addend.double()
            k >>= 1
        return result

    __rmul__ = __mul__

    def in_subgroup(self) -> bool:
        return (self * R).is_infinity()

    def __repr__(self):
        a = self.affine()
        return f"Point(infinity)" if a is None else f"Point({a[0]!r}, {a[1]!r})"


def msm(points: list, scalars: list) -> Point:
    """Pippenger multi-scalar multiplication.

    The pure-Python counterpart of the reference's arkworks
    `multiexp_unchecked` (SURVEY.md §2.2) and the algorithmic blueprint for
    the TPU bucket-accumulation kernel (ops/).  ~c-bit windows over 255-bit
    scalars with bucket accumulation per window.
    """
    if len(points) != len(scalars):
        raise ValueError("msm: length mismatch")
    pairs = [(p, int(s) % R) for p, s in zip(points, scalars)
             if int(s) % R != 0 and not p.is_infinity()]
    if not pairs:
        base = points[0].b if points else B1
        return Point.infinity(base)
    points = [p for p, _ in pairs]
    scalars = [s for _, s in pairs]
    n = len(points)
    c = 8 if n >= 128 else (4 if n >= 8 else 1)
    mask = (1 << c) - 1
    num_windows = (255 + c) // c
    window_sums = []
    for w in range(num_windows):
        shift = w * c
        buckets: list = [None] * mask
        for p, s in zip(points, scalars):
            idx = (s >> shift) & mask
            if idx:
                buckets[idx - 1] = p if buckets[idx - 1] is None \
                    else buckets[idx - 1] + p
        running = Point.infinity(points[0].b)
        acc = Point.infinity(points[0].b)
        for b in reversed(buckets):
            if b is not None:
                running = running + b
            acc = acc + running
        window_sums.append(acc)
    result = window_sums[-1]
    for ws in reversed(window_sums[:-1]):
        for _ in range(c):
            result = result.double()
        result = result + ws
    return result


def g1_generator() -> Point:
    return Point(G1_X, G1_Y, Fq1.one(), B1)


def g2_generator() -> Point:
    return Point(G2_X, G2_Y, Fq2.one(), B2)


def g1_infinity() -> Point:
    return Point.infinity(B1)


def g2_infinity() -> Point:
    return Point.infinity(B2)


# ---------------------------------------------------------------------------
# ZCash compressed serialization
# ---------------------------------------------------------------------------

_HALF_Q = (Q - 1) // 2


def _y_sign_fq(y: Fq1) -> bool:
    return y.v > _HALF_Q


def _y_sign_fq2(y: Fq2) -> bool:
    # lexicographic on (c1, c0), c1 most significant
    if y.c1 != 0:
        return y.c1 > _HALF_Q
    return y.c0 > _HALF_Q


def g1_to_bytes(p: Point) -> bytes:
    if p.is_infinity():
        return bytes([0xC0]) + b"\x00" * 47
    x, y = p.affine()
    out = bytearray(x.v.to_bytes(48, "big"))
    out[0] |= 0x80
    if _y_sign_fq(y):
        out[0] |= 0x20
    return bytes(out)


def g2_to_bytes(p: Point) -> bytes:
    if p.is_infinity():
        return bytes([0xC0]) + b"\x00" * 95
    x, y = p.affine()
    out = bytearray(x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big"))
    out[0] |= 0x80
    if _y_sign_fq2(y):
        out[0] |= 0x20
    return bytes(out)


class DecodeError(ValueError):
    pass


def _parse_flags(data: bytes, size: int):
    if len(data) != size:
        raise DecodeError(f"need {size} bytes, got {len(data)}")
    compression = bool(data[0] & 0x80)
    infinity = bool(data[0] & 0x40)
    sign = bool(data[0] & 0x20)
    if not compression:
        raise DecodeError("only compressed encodings are supported")
    return infinity, sign


def g1_from_bytes(data: bytes, subgroup_check: bool = True) -> Point:
    infinity, sign = _parse_flags(data, 48)
    body = bytes([data[0] & 0x1F]) + data[1:]
    if infinity:
        if any(body) or sign:
            raise DecodeError("malformed infinity encoding")
        return g1_infinity()
    x = int.from_bytes(body, "big")
    if x >= Q:
        raise DecodeError("x out of range")
    xf = Fq1(x)
    y2 = xf.square() * xf + B1
    y = y2.sqrt()
    if y is None:
        raise DecodeError("x not on curve")
    if _y_sign_fq(y) != sign:
        y = -y
    p = Point(xf, y, Fq1.one(), B1)
    if subgroup_check and not p.in_subgroup():
        raise DecodeError("point not in G1 subgroup")
    return p


def g2_from_bytes(data: bytes, subgroup_check: bool = True) -> Point:
    infinity, sign = _parse_flags(data, 96)
    body = bytes([data[0] & 0x1F]) + data[1:]
    if infinity:
        if any(body) or sign:
            raise DecodeError("malformed infinity encoding")
        return g2_infinity()
    c1 = int.from_bytes(body[:48], "big")
    c0 = int.from_bytes(body[48:], "big")
    if c0 >= Q or c1 >= Q:
        raise DecodeError("x out of range")
    xf = Fq2(c0, c1)
    y2 = xf.square() * xf + B2
    y = y2.sqrt()
    if y is None:
        raise DecodeError("x not on curve")
    if _y_sign_fq2(y) != sign:
        y = -y
    p = Point(xf, y, Fq2.one(), B2)
    if subgroup_check and not p.in_subgroup():
        raise DecodeError("point not in G2 subgroup")
    return p


def not_on_curve_x_g1() -> bytes:
    """48-byte compressed encoding whose x IS a canonical field element
    but x^3+4 is a quadratic non-residue — guaranteed to exercise the
    decompression (sqrt-failure) reject path rather than the subgroup
    check.  Deterministic: smallest such x.  Test-vector helper
    (reference bls/kzg generators use hand-picked equivalents)."""
    x = 2
    while fq_sqrt((x * x * x + 4) % Q) is not None:
        x += 1
    enc = bytearray(x.to_bytes(48, "big"))
    enc[0] |= 0x80
    return bytes(enc)


def not_on_curve_x_g2() -> bytes:
    """96-byte compressed G2 encoding with x=(c0, 0) chosen so
    x^3+4(1+u) has no Fq2 square root (same rationale as
    :func:`not_on_curve_x_g1`)."""
    c = 2
    while (Fq2(c, 0).square() * Fq2(c, 0) + B2).sqrt() is not None:
        c += 1
    enc = bytearray((0).to_bytes(48, "big") + c.to_bytes(48, "big"))
    enc[0] |= 0x80
    return bytes(enc)
