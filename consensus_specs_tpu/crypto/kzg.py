"""KZG polynomial commitments over the Ethereum ceremony trusted setup.

From-scratch implementation of the deneb KZG library
(/root/reference/specs/deneb/polynomial-commitments.md — function names and
Fiat-Shamir transcripts match section by section; docstrings cite lines).
Field arithmetic is plain ints mod BLS_MODULUS (= the BLS12-381 subgroup
order); curve work routes through crypto.curve incl. Pippenger MSM.  Batch
modular inversion accelerates barycentric evaluation without changing
results.  The TPU path (ops/) replaces the MSM and per-element field ops.
"""
from __future__ import annotations

import json
import os
from functools import lru_cache

from .fields import R as BLS_MODULUS
from . import curve as cv
from .curve import Point, msm
from ..utils.hash import hash as sha256

BYTES_PER_FIELD_ELEMENT = 32
KZG_ENDIANNESS = "big"
PRIMITIVE_ROOT_OF_UNITY = 7

FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"

G1_POINT_AT_INFINITY = bytes([0xC0]) + b"\x00" * 47

_SETUP_PATH = os.path.join(os.path.dirname(__file__), "..", "config",
                           "trusted_setups", "trusted_setup_4096.json")

# pluggable device MSM (the arkworks-multiexp slot of the reference's
# backend stack): installed by use_tpu_msm(), used by g1_lincomb for big
# batches
_device_msm = None
_device_msm_threshold = 128


def set_device_msm(fn, threshold: int = 128) -> None:
    """Install a device MSM `fn(points, scalars) -> Point` (None to
    uninstall)."""
    global _device_msm, _device_msm_threshold
    _device_msm = fn
    _device_msm_threshold = threshold


def use_tpu_msm(threshold: int = 128) -> None:
    from ..ops.msm import g1_multi_exp
    set_device_msm(g1_multi_exp, threshold)


class FieldMath:
    """Scalar-field helpers (polynomial-commitments.md "BLS field")."""

    @staticmethod
    def inverse(x: int) -> int:
        return pow(x % BLS_MODULUS, BLS_MODULUS - 2, BLS_MODULUS)

    @staticmethod
    def div(x: int, y: int) -> int:
        return x * FieldMath.inverse(y) % BLS_MODULUS

    @staticmethod
    def batch_inverse(xs: list[int]) -> list[int]:
        """Montgomery batch inversion: one pow, 3n muls. Zero maps to zero
        like pow(0, p-2) would."""
        prefix = []
        acc = 1
        for x in xs:
            prefix.append(acc)
            if x % BLS_MODULUS != 0:
                acc = acc * x % BLS_MODULUS
        inv = FieldMath.inverse(acc)
        out = [0] * len(xs)
        for i in range(len(xs) - 1, -1, -1):
            x = xs[i] % BLS_MODULUS
            if x == 0:
                out[i] = 0
            else:
                out[i] = prefix[i] * inv % BLS_MODULUS
                inv = inv * x % BLS_MODULUS
        # prefix[i] above includes only nonzero factors before i; recompute
        # correctness by construction: prefix products skip zeros, and so
        # does the suffix unwind.
        return out


def compute_powers(x: int, n: int) -> list[int]:
    powers = []
    current = 1
    for _ in range(n):
        powers.append(current)
        current = current * x % BLS_MODULUS
    return powers


def hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(sha256(data), KZG_ENDIANNESS) % BLS_MODULUS


def bytes_to_bls_field(b: bytes) -> int:
    # the spec types this input Bytes32 (deneb/polynomial-commitments.md
    # bytes_to_bls_field) — enforce the length the type system would
    if len(b) != 32:
        raise ValueError("field element must be exactly 32 bytes")
    x = int.from_bytes(bytes(b), KZG_ENDIANNESS)
    if x >= BLS_MODULUS:
        raise ValueError("field element out of range")
    return x


def bls_field_to_bytes(x: int) -> bytes:
    return int(x).to_bytes(32, KZG_ENDIANNESS)


class KZG:
    """A KZG engine bound to one trusted setup + blob width."""

    def __init__(self, field_elements_per_blob: int = 4096,
                 setup_path: str = _SETUP_PATH, setup: dict | None = None):
        self.width = field_elements_per_blob
        if setup is None:
            with open(setup_path) as f:
                setup = json.load(f)
        self._g1_lagrange_bytes = [bytes.fromhex(h[2:])
                                   for h in setup["g1_lagrange"]]
        self._g1_monomial_bytes = [bytes.fromhex(h[2:])
                                   for h in setup["g1_monomial"]]
        self._g2_monomial_bytes = [bytes.fromhex(h[2:])
                                   for h in setup["g2_monomial"]]
        assert len(self._g1_lagrange_bytes) == self.width
        self._g1_lagrange_brp: list[Point] | None = None
        self._g2_monomial: list[Point] | None = None
        self._roots_brp: tuple | None = None

    # -- setup access (decompressed lazily; ceremony output is trusted,
    #    so no per-point subgroup check here)
    def g1_lagrange_brp(self) -> list[Point]:
        if self._g1_lagrange_brp is None:
            pts = [cv.g1_from_bytes(b, subgroup_check=False)
                   for b in self._g1_lagrange_bytes]
            self._g1_lagrange_brp = bit_reversal_permutation(pts)
        return self._g1_lagrange_brp

    def g2_monomial(self) -> list[Point]:
        if self._g2_monomial is None:
            self._g2_monomial = [cv.g2_from_bytes(b, subgroup_check=False)
                                 for b in self._g2_monomial_bytes]
        return self._g2_monomial

    # -- domain
    def _roots_of_unity_brp(self) -> tuple:
        """Roots of unity in bit-reversal order (the blob evaluation
        domain), polynomial-commitments.md compute_roots_of_unity +
        bit_reversal_permutation (:142)."""
        if self._roots_brp is None:
            root = pow(PRIMITIVE_ROOT_OF_UNITY,
                       (BLS_MODULUS - 1) // self.width, BLS_MODULUS)
            roots = compute_powers(root, self.width)
            assert root != 1 and pow(root, self.width, BLS_MODULUS) == 1
            self._roots_brp = tuple(bit_reversal_permutation(roots))
        return self._roots_brp

    # -- blob <-> polynomial
    def blob_to_polynomial(self, blob: bytes) -> list[int]:
        assert len(blob) == BYTES_PER_FIELD_ELEMENT * self.width
        return [bytes_to_bls_field(
            blob[i * 32:(i + 1) * 32]) for i in range(self.width)]

    def compute_challenge(self, blob: bytes, commitment: bytes) -> int:
        """Fiat-Shamir challenge (polynomial-commitments.md:249)."""
        degree_poly = self.width.to_bytes(16, KZG_ENDIANNESS)
        data = FIAT_SHAMIR_PROTOCOL_DOMAIN + degree_poly + bytes(blob) \
            + bytes(commitment)
        return hash_to_bls_field(data)

    # -- core polynomial ops
    def g1_lincomb(self, points: list[Point], scalars: list[int]) -> bytes:
        """MSM -> compressed bytes (polynomial-commitments.md:268).

        Routes through the device MSM kernel when installed and the batch
        is large enough to amortize transfer (set_device_msm); otherwise
        the host Pippenger oracle.  The device call rides the resilience
        dispatch seam with the host oracle as supervised fallback."""
        if _device_msm is not None and len(points) >= _device_msm_threshold:
            from ..resilience.supervisor import dispatch
            return cv.g1_to_bytes(dispatch(
                "ops.msm.kzg",
                lambda: _device_msm(points, scalars),
                lambda: msm(points, scalars)))
        return cv.g1_to_bytes(msm(points, scalars))

    def evaluate_polynomial_in_evaluation_form(self, polynomial: list[int],
                                               z: int) -> int:
        """Barycentric evaluation at z (polynomial-commitments.md:317)."""
        width = self.width
        assert len(polynomial) == width
        inverse_width = FieldMath.inverse(width)
        roots = self._roots_of_unity_brp()
        # z on the domain: the evaluation is just the stored value
        if z in roots:
            return polynomial[roots.index(z)]
        denominators = [(z - r) % BLS_MODULUS for r in roots]
        inv_denoms = FieldMath.batch_inverse(denominators)
        result = 0
        for i in range(width):
            result += polynomial[i] * roots[i] % BLS_MODULUS \
                * inv_denoms[i] % BLS_MODULUS
        result = result % BLS_MODULUS \
            * (pow(z, width, BLS_MODULUS) - 1) % BLS_MODULUS \
            * inverse_width % BLS_MODULUS
        return result % BLS_MODULUS

    # -- commitments & proofs
    def blob_to_kzg_commitment(self, blob: bytes) -> bytes:
        """polynomial-commitments.md:353"""
        return self.g1_lincomb(self.g1_lagrange_brp(),
                               self.blob_to_polynomial(blob))

    def compute_quotient_eval_within_domain(self, z: int,
                                            polynomial: list[int],
                                            y: int) -> int:
        """Quotient at a domain point (the removable singularity case)."""
        roots = self._roots_of_unity_brp()
        result = 0
        for i, omega_i in enumerate(roots):
            if omega_i == z:
                continue
            f_i = (polynomial[i] - y) % BLS_MODULUS
            numerator = f_i * omega_i % BLS_MODULUS
            denominator = z * (z - omega_i) % BLS_MODULUS
            result += FieldMath.div(numerator, denominator)
        return result % BLS_MODULUS

    def compute_kzg_proof_impl(self, polynomial: list[int],
                               z: int) -> tuple[bytes, int]:
        """polynomial-commitments.md:466 — returns (proof, y)."""
        roots = self._roots_of_unity_brp()
        y = self.evaluate_polynomial_in_evaluation_form(polynomial, z)
        polynomial_shifted = [(p - y) % BLS_MODULUS for p in polynomial]
        denominator_poly = [(r - z) % BLS_MODULUS for r in roots]
        inv_denoms = FieldMath.batch_inverse(denominator_poly)
        quotient_polynomial = [0] * self.width
        for i in range(self.width):
            if denominator_poly[i] == 0:
                quotient_polynomial[i] = \
                    self.compute_quotient_eval_within_domain(
                        roots[i], polynomial, y)
            else:
                quotient_polynomial[i] = \
                    polynomial_shifted[i] * inv_denoms[i] % BLS_MODULUS
        proof = self.g1_lincomb(self.g1_lagrange_brp(), quotient_polynomial)
        return proof, y

    def compute_kzg_proof(self, blob: bytes,
                          z_bytes: bytes) -> tuple[bytes, bytes]:
        polynomial = self.blob_to_polynomial(blob)
        proof, y = self.compute_kzg_proof_impl(
            polynomial, bytes_to_bls_field(z_bytes))
        return proof, bls_field_to_bytes(y)

    def compute_blob_kzg_proof(self, blob: bytes,
                               commitment_bytes: bytes) -> bytes:
        """polynomial-commitments.md:523"""
        self.validate_kzg_g1(commitment_bytes)
        challenge = self.compute_challenge(blob, commitment_bytes)
        proof, _ = self.compute_kzg_proof_impl(
            self.blob_to_polynomial(blob), challenge)
        return proof

    # -- verification
    @staticmethod
    def validate_kzg_g1(b: bytes) -> None:
        """Subgroup/format validation of untrusted G1 bytes
        (polynomial-commitments.md validate_kzg_g1)."""
        if bytes(b) == G1_POINT_AT_INFINITY:
            return
        cv.g1_from_bytes(bytes(b), subgroup_check=True)

    def verify_kzg_proof_impl(self, commitment: bytes, z: int, y: int,
                              proof: bytes) -> bool:
        """e(C - [y]G1, G2) == e(proof, [tau - z]G2)
        (polynomial-commitments.md:383)."""
        g2 = cv.g2_generator()
        x_minus_z = self.g2_monomial()[1] + g2 * ((BLS_MODULUS - z)
                                                  % BLS_MODULUS)
        p_minus_y = cv.g1_from_bytes(bytes(commitment),
                                     subgroup_check=False) \
            + cv.g1_generator() * ((BLS_MODULUS - y) % BLS_MODULUS)
        from .pairing import pairing_check
        return pairing_check([(p_minus_y, -g2),
                              (cv.g1_from_bytes(bytes(proof),
                                                subgroup_check=False),
                               x_minus_z)])

    def verify_kzg_proof(self, commitment_bytes: bytes, z_bytes: bytes,
                         y_bytes: bytes, proof_bytes: bytes) -> bool:
        self.validate_kzg_g1(commitment_bytes)
        self.validate_kzg_g1(proof_bytes)
        return self.verify_kzg_proof_impl(
            commitment_bytes,
            bytes_to_bls_field(z_bytes),
            bytes_to_bls_field(y_bytes),
            proof_bytes)

    def compute_r_powers(self, commitments, zs, ys, proofs) -> list[int]:
        """Batch-verification challenge powers
        (polynomial-commitments.md:427)."""
        n = len(commitments)
        data = RANDOM_CHALLENGE_KZG_BATCH_DOMAIN \
            + self.width.to_bytes(8, KZG_ENDIANNESS) \
            + n.to_bytes(8, KZG_ENDIANNESS)
        for commitment, z, y, proof in zip(commitments, zs, ys, proofs):
            data += bytes(commitment) + bls_field_to_bytes(z) \
                + bls_field_to_bytes(y) + bytes(proof)
        r = hash_to_bls_field(data)
        return compute_powers(r, n)

    def verify_kzg_proof_batch(self, commitments, zs, ys, proofs) -> bool:
        """Random-linear-combination batch check with one pairing
        (polynomial-commitments.md:410).

        The three shared-base lincombs ride the `ops.pairing_fold`
        seam (sigpipe/fold.fold_kzg_lincombs) — the same supervised
        shape as the signature fold, with the counted host ladder as
        byte-identical fallback; FOLD_VERIFY=0 keeps the plain host
        msm() calls byte-for-byte."""
        assert len(commitments) == len(zs) == len(ys) == len(proofs)
        proof_points = [cv.g1_from_bytes(bytes(p), subgroup_check=False)
                        for p in proofs]
        c_minus_ys = [
            cv.g1_from_bytes(bytes(c), subgroup_check=False)
            + cv.g1_generator() * ((BLS_MODULUS - y) % BLS_MODULUS)
            for c, y in zip(commitments, ys)]
        r_powers = self.compute_r_powers(commitments, zs, ys, proofs)
        r_times_z = [r * z % BLS_MODULUS for r, z in zip(r_powers, zs)]

        # lazy: crypto/ must not import sigpipe/ at module load (the
        # scheduler imports crypto right back)
        from ..sigpipe import fold
        if fold.live():
            proof_lincomb, proof_z_lincomb, c_minus_y_lincomb = \
                fold.fold_kzg_lincombs(proof_points, c_minus_ys,
                                       r_powers, r_times_z)
        else:
            proof_lincomb = msm(proof_points, r_powers)
            proof_z_lincomb = msm(proof_points, r_times_z)
            c_minus_y_lincomb = msm(c_minus_ys, r_powers)

        from .pairing import pairing_check
        g2 = cv.g2_generator()
        return pairing_check([
            (c_minus_y_lincomb + proof_z_lincomb, -g2),
            (proof_lincomb, self.g2_monomial()[1]),
        ])

    def verify_blob_kzg_proof(self, blob: bytes, commitment_bytes: bytes,
                              proof_bytes: bytes) -> bool:
        """polynomial-commitments.md:544"""
        self.validate_kzg_g1(commitment_bytes)
        self.validate_kzg_g1(proof_bytes)
        challenge = self.compute_challenge(blob, commitment_bytes)
        polynomial = self.blob_to_polynomial(blob)
        y = self.evaluate_polynomial_in_evaluation_form(polynomial,
                                                        challenge)
        return self.verify_kzg_proof_impl(commitment_bytes, challenge, y,
                                          proof_bytes)

    def verify_blob_kzg_proof_batch(self, blobs, commitments,
                                    proofs) -> bool:
        """North-star config #4 (polynomial-commitments.md:569).

        With folding live the N blobs cost ONE 2-leg pairing (the RLC
        batch, its lincombs on the `ops.pairing_fold` seam), observed
        in `kzg_pairing_legs`; FOLD_VERIFY=0 is the escape hatch back
        to N per-blob 2-leg checks, byte-identical verdicts.  A batch
        that fails re-runs per-blob so the REJECTION is attributed to
        specific blobs (`kzg_batch_attributions`) instead of one
        opaque product — degraded cost, never a degraded verdict."""
        assert len(blobs) == len(commitments) == len(proofs)
        evaluation_challenges = []
        ys = []
        for blob, commitment in zip(blobs, commitments):
            self.validate_kzg_g1(commitment)
            challenge = self.compute_challenge(blob, commitment)
            polynomial = self.blob_to_polynomial(blob)
            evaluation_challenges.append(challenge)
            ys.append(self.evaluate_polynomial_in_evaluation_form(
                polynomial, challenge))
        for proof in proofs:
            self.validate_kzg_g1(proof)
        # lazy for the same crypto<->sigpipe cycle as the batch check
        from ..sigpipe import fold
        from ..sigpipe.metrics import METRICS
        n = len(blobs)
        if not fold.live():
            METRICS.observe("kzg_pairing_legs", 2 * max(n, 1))
            return all(
                self.verify_kzg_proof_impl(c, z, y, p)
                for c, z, y, p in zip(commitments, evaluation_challenges,
                                      ys, proofs))
        ok = self.verify_kzg_proof_batch(
            commitments, evaluation_challenges, ys, proofs)
        METRICS.observe("kzg_pairing_legs", 2)
        if ok:
            return True
        # the RLC product only says "some blob lied" — degrade to
        # per-blob checks so the verdict names the liars
        METRICS.inc("kzg_batch_attributions")
        METRICS.observe("kzg_pairing_legs", 2 * max(n, 1))
        return all(
            self.verify_kzg_proof_impl(c, z, y, p)
            for c, z, y, p in zip(commitments, evaluation_challenges,
                                  ys, proofs))


@lru_cache(maxsize=4)
def get_kzg(field_elements_per_blob: int = 4096) -> KZG:
    return KZG(field_elements_per_blob)


def bit_reversal_permutation(sequence: list) -> list:
    """Reorder by bit-reversed index (polynomial-commitments.md:142)."""
    n = len(sequence)
    assert n & (n - 1) == 0, "length must be a power of two"
    bits = n.bit_length() - 1
    return [sequence[int(format(i, f"0{bits}b")[::-1], 2)]
            for i in range(n)]
