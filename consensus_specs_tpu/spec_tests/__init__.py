"""Dual-mode spec test suites.

Every module here holds `@spec_state_test`-decorated generator functions:
under pytest the yields are drained and the asserts run; under the vector
generator the same bodies stream their artifacts to disk as conformance
vectors (the reference's single-test-body/two-modes architecture,
SURVEY.md §4).  tests/test_spec_suites.py collects them for pytest;
gen/runners/* reflect them via gen.reflect.generate_from_tests.
"""
