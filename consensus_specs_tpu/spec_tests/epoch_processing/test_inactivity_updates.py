"""process_inactivity_updates epoch tests (altair+; reference:
test/altair/epoch_processing/test_process_inactivity_updates.py —
score movement under the {zero, random} x {empty, random, full}
participation x {leaking, finalizing} matrix).
"""
import random as _random

from ...ssz import uint64
from ...test_infra.context import (
    never_bls, spec_state_test, with_all_phases_from)
from ...test_infra.blocks import transition_to
from ...test_infra.epoch_processing import run_epoch_processing_with

FLAG_COUNT = 3


def _full_flags(spec) -> int:
    flags = 0
    for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        flags = spec.add_flag(flags, i)
    return flags


def _set_leaking(spec, state) -> None:
    target = (int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3) * \
        int(spec.SLOTS_PER_EPOCH)
    transition_to(spec, state, uint64(target))
    assert spec.is_in_inactivity_leak(state)


def _participation(spec, state, mode: str, rng=None) -> None:
    n = len(state.validators)
    full = _full_flags(spec)
    if mode == "full":
        vals = [full] * n
    elif mode == "empty":
        vals = [0] * n
    else:
        vals = [rng.randrange(0, full + 1) for _ in range(n)]
    state.previous_epoch_participation = vals


def _scores(spec, state, mode: str, rng=None) -> None:
    n = len(state.validators)
    if mode == "zero":
        state.inactivity_scores = [0] * n
    else:
        state.inactivity_scores = [
            uint64(rng.randrange(0, 100)) for _ in range(n)]


def _run_case(spec, state, scores: str, participation: str,
              leaking: bool, seed: str, mutate=None):
    rng = _random.Random(f"{spec.fork}:{seed}")
    if leaking:
        _set_leaking(spec, state)
    else:
        transition_to(spec, state, uint64(2 * spec.SLOTS_PER_EPOCH))
        # keep finality fresh so the leak is off
        state.finalized_checkpoint.epoch = uint64(
            max(int(spec.get_current_epoch(state)) - 2, 0))
    _participation(spec, state, participation, rng)
    _scores(spec, state, scores, rng)
    if mutate is not None:
        mutate(rng)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_genesis(spec, state):
    """At the genesis epoch the pass is a no-op."""
    pre = list(state.inactivity_scores)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert list(state.inactivity_scores) == pre


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_all_zero_scores_empty_participation(spec, state):
    yield from _run_case(spec, state, "zero", "empty", False, "s1")
    assert all(int(s) == 0 for s in state.inactivity_scores)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_all_zero_scores_empty_participation_leaking(spec, state):
    yield from _run_case(spec, state, "zero", "empty", True, "s2")
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    assert all(int(s) == bias for s in state.inactivity_scores)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_all_zero_scores_random_participation(spec, state):
    yield from _run_case(spec, state, "zero", "random", False, "s3")
    assert all(int(s) == 0 for s in state.inactivity_scores)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_all_zero_scores_random_participation_leaking(spec, state):
    yield from _run_case(spec, state, "zero", "random", True, "s4")


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_all_zero_scores_full_participation(spec, state):
    yield from _run_case(spec, state, "zero", "full", False, "s5")
    assert all(int(s) == 0 for s in state.inactivity_scores)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_all_zero_scores_full_participation_leaking(spec, state):
    """Target-participating validators never accrue score, leak or
    not."""
    yield from _run_case(spec, state, "zero", "full", True, "s6")
    assert all(int(s) == 0 for s in state.inactivity_scores)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_random_scores_empty_participation(spec, state):
    """No leak: scores decay by the recovery rate, never below 0."""
    yield from _run_case(spec, state, "random", "empty", False, "s7")


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_random_scores_empty_participation_leaking(spec, state):
    yield from _run_case(spec, state, "random", "empty", True, "s8")


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_random_scores_random_participation(spec, state):
    yield from _run_case(spec, state, "random", "random", False, "s9")


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_random_scores_random_participation_leaking(spec, state):
    yield from _run_case(spec, state, "random", "random", True, "s10")


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_random_scores_full_participation_leaking(spec, state):
    """During a leak, participating validators shed exactly 1 score
    point (the recovery-rate decay is gated on NOT leaking)."""
    staged = []
    yield from _run_case(spec, state, "random", "full", True, "s11",
                         mutate=_snapshot_scores(state, staged))
    pre_done = dict(enumerate(staged))
    for i, s in enumerate(state.inactivity_scores):
        assert int(s) == max(pre_done[i] - 1, 0)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_some_slashed_zero_scores_full_participation_leaking(spec,
                                                             state):
    """Slashed validators cannot earn target credit: their scores climb
    during a leak despite full participation flags."""
    yield from _run_case(spec, state, "zero", "full", True, "s12",
                         mutate=_slash_quarter(spec, state))
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    for i, s in enumerate(state.inactivity_scores):
        assert int(s) == (bias if i % 4 == 0 else 0)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_some_exited_full_random_leaking(spec, state):
    def exit_some(rng):
        cur = int(spec.get_current_epoch(state))
        for i in range(0, len(state.validators), 5):
            state.validators[i].exit_epoch = uint64(max(cur - 1, 0))
            state.validators[i].withdrawable_epoch = uint64(cur + 10)
    yield from _run_case(spec, state, "random", "random", True, "s13",
                         mutate=exit_some)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_randomized_state_leaking(spec, state):
    from ...test_infra.random import randomize_state, rng_for
    def scramble(_rng):
        randomize_state(spec, state, rng_for(spec, seed=0xABCD))
    yield from _run_case(spec, state, "random", "random", True, "s14",
                         mutate=scramble)


def _snapshot_scores(state, out):
    """mutate-hook: record the staged scores before the pass runs."""
    def capture(_rng):
        out.extend(int(s) for s in state.inactivity_scores)
    return capture


def _slash_quarter(spec, state):
    """mutate-hook: slash every 4th validator with the withdrawable
    epoch inside the slashing window."""
    def slash(_rng):
        for i in range(0, len(state.validators), 4):
            state.validators[i].slashed = True
            state.validators[i].withdrawable_epoch = uint64(
                int(spec.get_current_epoch(state))
                + int(spec.EPOCHS_PER_SLASHINGS_VECTOR))
    return slash


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_genesis_random_scores(spec, state):
    """At the genesis epoch the pass is a no-op even with nonzero
    scores staged."""
    rng = _random.Random(f"{spec.fork}:s15")
    _scores(spec, state, "random", rng)
    pre = list(int(s) for s in state.inactivity_scores)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert [int(s) for s in state.inactivity_scores] == pre


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_random_scores_full_participation(spec, state):
    """Not leaking + fully participating: every score decays by
    exactly min(1, s) + min(recovery, remaining)."""
    staged = []
    yield from _run_case(spec, state, "random", "full", False, "s16",
                         mutate=_snapshot_scores(state, staged))
    rec = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    for s, pre in zip(state.inactivity_scores, staged):
        after_flag = pre - min(1, pre)
        assert int(s) == after_flag - min(rec, after_flag)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_some_slashed_zero_scores_full_participation(spec, state):
    """Without a leak, a slashed validator accrues the bias but then
    recovers min(recovery, score) in the same pass — with the shipped
    presets (bias 4 <= recovery 16) the score lands back at zero."""
    yield from _run_case(spec, state, "zero", "full", False, "s17",
                         mutate=_slash_quarter(spec, state))
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    rec = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    expected = max(bias - rec, 0)
    for i, s in enumerate(state.inactivity_scores):
        if state.validators[i].slashed:
            assert int(s) == expected
        else:
            assert int(s) == 0


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_some_slashed_full_random(spec, state):
    yield from _run_case(spec, state, "random", "random", False, "s18",
                         mutate=_slash_quarter(spec, state))


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_some_slashed_full_random_leaking(spec, state):
    yield from _run_case(spec, state, "random", "random", True, "s19",
                         mutate=_slash_quarter(spec, state))


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_randomized_state(spec, state):
    from ...test_infra.random import randomize_state, rng_for
    def scramble(_rng):
        randomize_state(spec, state, rng_for(spec, seed=0xBCDE))
    yield from _run_case(spec, state, "random", "random", False, "s20",
                         mutate=scramble)
