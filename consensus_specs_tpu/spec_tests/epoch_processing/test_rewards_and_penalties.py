"""process_rewards_and_penalties epoch battery (altair+; reference
test/*/epoch_processing/test_process_rewards_and_penalties.py, 19 defs
across forks): participation shapes x leak, genesis-epoch no-ops,
slashed exclusions, balance diversity.

Participation is staged directly on the flag registers (altair's
accounting input) — the attestation-to-flag path is covered by the
operations battery and the phase0 pending-attestation form by the
rewards package."""
import random

from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, with_custom_state,
    misc_balances, default_activation_threshold)
from ...test_infra.blocks import transition_to
from ...test_infra.epoch_processing import run_epoch_processing_with

FULL_FLAGS = 0b111


def _set_participation(spec, state, fn):
    """previous-epoch participation per validator index via `fn(i)`."""
    for i in range(len(state.validators)):
        state.previous_epoch_participation[i] = fn(i)


def _advance_epochs(spec, state, n):
    transition_to(spec, state,
                  uint64(int(state.slot)
                         + n * int(spec.SLOTS_PER_EPOCH)))


def _induce_leak(spec, state):
    """Past the inactivity-leak threshold with finality stuck at 0."""
    _advance_epochs(spec, state,
                    int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2)
    assert spec.is_in_inactivity_leak(state)


@with_all_phases_from("altair")
@spec_state_test
def test_full_attestation_participation(spec, state):
    _advance_epochs(spec, state, 2)
    _set_participation(spec, state, lambda i: FULL_FLAGS)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    assert all(int(b) > p for b, p in zip(state.balances, pre))


@with_all_phases_from("altair")
@spec_state_test
def test_full_attestation_participation_with_leak(spec, state):
    _induce_leak(spec, state)
    _set_participation(spec, state, lambda i: FULL_FLAGS)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    # leak: no attestation rewards — full participants stay flat
    assert all(int(b) == p for b, p in zip(state.balances, pre))


@with_all_phases_from("altair")
@spec_state_test
def test_almost_empty_attestations(spec, state):
    _advance_epochs(spec, state, 2)
    _set_participation(spec, state,
                       lambda i: FULL_FLAGS if i == 0 else 0)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    assert int(state.balances[0]) > pre[0]
    assert all(int(state.balances[i]) < pre[i]
               for i in range(1, len(pre)))


@with_all_phases_from("altair")
@spec_state_test
def test_almost_empty_attestations_with_leak(spec, state):
    _induce_leak(spec, state)
    _set_participation(spec, state,
                       lambda i: FULL_FLAGS if i == 0 else 0)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    # leaking: non-participants bleed (flag penalties + inactivity)
    assert all(int(state.balances[i]) < pre[i]
               for i in range(1, len(pre)))


@with_all_phases_from("altair")
@spec_state_test
def test_almost_full_attestations(spec, state):
    _advance_epochs(spec, state, 2)
    _set_participation(spec, state,
                       lambda i: 0 if i == 0 else FULL_FLAGS)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    assert int(state.balances[0]) < pre[0]
    assert all(int(state.balances[i]) > pre[i]
               for i in range(1, len(pre)))


@with_all_phases_from("altair")
@spec_state_test
def test_almost_full_attestations_with_leak(spec, state):
    _induce_leak(spec, state)
    _set_participation(spec, state,
                       lambda i: 0 if i == 0 else FULL_FLAGS)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    assert int(state.balances[0]) < pre[0]
    assert all(int(state.balances[i]) == pre[i]
               for i in range(1, len(pre)))


@with_all_phases_from("altair")
@spec_state_test
def test_no_attestations_all_penalties(spec, state):
    _advance_epochs(spec, state, 2)
    _set_participation(spec, state, lambda i: 0)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    assert all(int(b) < p for b, p in zip(state.balances, pre))


@with_all_phases_from("altair")
@spec_state_test
def test_genesis_epoch_no_attestations_no_penalties(spec, state):
    assert int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    # the pass is a no-op during the genesis epoch
    assert all(int(b) == p for b, p in zip(state.balances, pre))


@with_all_phases_from("altair")
@spec_state_test
def test_genesis_epoch_full_attestations_no_rewards(spec, state):
    assert int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH)
    _set_participation(spec, state, lambda i: FULL_FLAGS)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    assert all(int(b) == p for b, p in zip(state.balances, pre))


@with_all_phases_from("altair")
@spec_state_test
def test_attestations_some_slashed(spec, state):
    """Slashed validators earn nothing even with full flags set."""
    _advance_epochs(spec, state, 2)
    _set_participation(spec, state, lambda i: FULL_FLAGS)
    epoch = int(spec.get_current_epoch(state))
    for i in range(0, 4):
        state.validators[i].slashed = True
        state.validators[i].withdrawable_epoch = uint64(
            epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR))
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    # slashed: denied participation rewards AND penalized as absent
    for i in range(0, 4):
        assert int(state.balances[i]) < pre[i]
    assert all(int(state.balances[i]) > pre[i]
               for i in range(4, len(pre)))


@with_all_phases_from("altair")
@with_custom_state(misc_balances, default_activation_threshold)
@spec_state_test
def test_full_attestations_misc_balances(spec, state):
    _advance_epochs(spec, state, 2)
    _set_participation(spec, state, lambda i: FULL_FLAGS)
    eligible = [i for i in range(len(state.validators))
                if spec.is_active_validator(
                    state.validators[i], spec.get_previous_epoch(state))]
    assert eligible
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    assert all(int(state.balances[i]) > pre[i] for i in eligible)


@with_all_phases_from("altair")
@spec_state_test
def test_full_attestations_one_validator_one_gwei(spec, state):
    _advance_epochs(spec, state, 2)
    _set_participation(spec, state, lambda i: FULL_FLAGS)
    state.balances[4] = uint64(1)
    state.validators[4].effective_balance = uint64(0)
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
    # zero effective balance: zero base reward, balance unchanged
    assert int(state.balances[4]) == pre[4]


def _random_fill(spec, state, rng):
    _set_participation(
        spec, state,
        lambda i: rng.choice((0, 0b001, 0b011, 0b111)))


@with_all_phases_from("altair")
@spec_state_test
def test_random_fill_attestations(spec, state):
    _advance_epochs(spec, state, 2)
    _random_fill(spec, state, random.Random(4040))
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")


@with_all_phases_from("altair")
@spec_state_test
def test_random_fill_attestations_with_leak(spec, state):
    _induce_leak(spec, state)
    _random_fill(spec, state, random.Random(4041))
    yield from run_epoch_processing_with(
        spec, state, "process_rewards_and_penalties")
