"""process_effective_balance_updates epoch tests (hysteresis)."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases, with_all_phases_from,
    with_custom_state,
    misc_balances, zero_activation_threshold)
from ...test_infra.epoch_processing import run_epoch_processing_with


@with_all_phases
@spec_state_test
def test_effective_balance_hysteresis(spec, state):
    """Balances nudged across / within the hysteresis thresholds."""
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    half_inc = inc // 2
    cases = [
        (max_eb, max_eb, max_eb),                       # as-is
        (max_eb, max_eb - 1, max_eb),                   # below but within
        (max_eb, max_eb - half_inc - 1, max_eb - inc),  # below threshold
        (max_eb, max_eb + 1, max_eb),                   # above but within
        (max_eb - inc, max_eb - 1, max_eb - inc),       # up within
        (max_eb - inc, max_eb + half_inc + inc // 4, max_eb),  # up across
    ]
    for i, (pre_eff, balance, _post_eff) in enumerate(cases):
        state.validators[i].effective_balance = uint64(pre_eff)
        state.balances[i] = uint64(balance)

    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")

    for i, (_pre_eff, _balance, post_eff) in enumerate(cases):
        assert int(state.validators[i].effective_balance) == post_eff, i


@with_all_phases
@with_custom_state(misc_balances, zero_activation_threshold)
@spec_state_test
def test_effective_balance_updates_misc_balances(spec, state):
    """The hysteresis sweep over a genesis built from the misc-balance
    shaper (mixed effective balances incl. ejection-level validators) —
    exercises the with_custom_state genesis machinery end-to-end."""
    pre_effs = [int(v.effective_balance) for v in state.validators]
    assert len(set(pre_effs)) > 2       # genuinely mixed registry
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    for i, v in enumerate(state.validators):
        eff = int(v.effective_balance)
        assert eff % inc == 0 and eff <= max_eb, i


@with_all_phases_from("electra")
@spec_state_test
def test_effective_balance_compounding_ceiling(spec, state):
    """Electra: 0x02 compounding credentials raise the effective-balance
    ceiling to MAX_EFFECTIVE_BALANCE_ELECTRA while 0x01 validators stay
    capped at MIN_ACTIVATION_BALANCE-scale MAX_EFFECTIVE_BALANCE."""
    from ...test_infra.withdrawals import (
        set_compounding_withdrawal_credentials,
        set_eth1_withdrawal_credentials)
    big = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    set_compounding_withdrawal_credentials(spec, state, 0)
    state.balances[0] = uint64(big + int(spec.EFFECTIVE_BALANCE_INCREMENT))
    set_eth1_withdrawal_credentials(spec, state, 1)
    state.balances[1] = uint64(big)   # same balance, non-compounding

    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")

    assert int(state.validators[0].effective_balance) == big
    assert int(state.validators[1].effective_balance) == \
        int(spec.MIN_ACTIVATION_BALANCE)


@with_all_phases
@spec_state_test
def test_effective_balance_zero_balance(spec, state):
    """A fully drained balance floors the effective balance at zero."""
    state.balances[0] = uint64(0)
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    assert int(state.validators[0].effective_balance) == 0


@with_all_phases
@spec_state_test
def test_effective_balance_exact_downward_threshold(spec, state):
    """Balance exactly AT effective - downward margin: stays (strict
    inequality)."""
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    down = inc * int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER) // \
        int(spec.HYSTERESIS_QUOTIENT)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    state.validators[0].effective_balance = uint64(max_eb)
    state.balances[0] = uint64(max_eb - down)
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    assert int(state.validators[0].effective_balance) == max_eb


@with_all_phases
@spec_state_test
def test_effective_balance_one_below_downward_threshold(spec, state):
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    down = inc * int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER) // \
        int(spec.HYSTERESIS_QUOTIENT)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    state.validators[0].effective_balance = uint64(max_eb)
    state.balances[0] = uint64(max_eb - down - 1)
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    assert int(state.validators[0].effective_balance) == max_eb - inc


@with_all_phases
@spec_state_test
def test_effective_balance_exact_upward_threshold(spec, state):
    """Balance exactly AT effective + upward margin: stays."""
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    up = inc * int(spec.HYSTERESIS_UPWARD_MULTIPLIER) // \
        int(spec.HYSTERESIS_QUOTIENT)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    pre = max_eb - 2 * inc
    state.validators[0].effective_balance = uint64(pre)
    state.balances[0] = uint64(pre + up)
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    assert int(state.validators[0].effective_balance) == pre


@with_all_phases
@spec_state_test
def test_effective_balance_one_above_upward_threshold(spec, state):
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    up = inc * int(spec.HYSTERESIS_UPWARD_MULTIPLIER) // \
        int(spec.HYSTERESIS_QUOTIENT)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    pre = max_eb - 2 * inc
    state.validators[0].effective_balance = uint64(pre)
    state.balances[0] = uint64(pre + up + 1)
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    assert int(state.validators[0].effective_balance) == pre + inc


@with_all_phases
@spec_state_test
def test_effective_balance_whole_registry_drifts(spec, state):
    """Every validator nudged randomly: post-effectives are all
    increment-quantized and within the ceiling."""
    import random as _r
    rng = _r.Random(f"{spec.fork}:eb-drift")
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    for i in range(len(state.validators)):
        state.balances[i] = uint64(
            max(int(state.balances[i]) + rng.randrange(-2 * inc,
                                                       2 * inc), 0))
    yield from run_epoch_processing_with(
        spec, state, "process_effective_balance_updates")
    for v in state.validators:
        assert int(v.effective_balance) % inc == 0
