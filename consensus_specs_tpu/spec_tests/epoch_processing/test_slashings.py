"""process_slashings epoch tests (correlation penalty)."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases
from ...test_infra.epoch_processing import run_epoch_processing_with


def _slash_validators_in_window(spec, state, indices):
    """Mark validators slashed with withdrawable_epoch in the penalty
    window and record slashed balance."""
    epoch = int(spec.get_current_epoch(state))
    total = 0
    for i in indices:
        v = state.validators[i]
        v.slashed = True
        v.withdrawable_epoch = uint64(
            epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2)
        total += int(v.effective_balance)
    state.slashings[epoch % spec.EPOCHS_PER_SLASHINGS_VECTOR] = \
        uint64(total)


@with_all_phases
@spec_state_test
def test_correlated_penalty(spec, state):
    n = len(state.validators)
    targets = list(range(0, n, max(1, n // 8)))[:8]
    _slash_validators_in_window(spec, state, targets)
    pre = [int(state.balances[i]) for i in targets]
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    for i, b in zip(targets, pre):
        assert int(state.balances[i]) <= b


@with_all_phases
@spec_state_test
def test_no_slashings_no_penalty(spec, state):
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    assert [int(b) for b in state.balances] == pre
