"""process_slashings epoch tests (correlation penalty)."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases
from ...test_infra.epoch_processing import run_epoch_processing_with


def _slash_validators_in_window(spec, state, indices):
    """Mark validators slashed with withdrawable_epoch in the penalty
    window and record slashed balance."""
    epoch = int(spec.get_current_epoch(state))
    total = 0
    for i in indices:
        v = state.validators[i]
        v.slashed = True
        v.withdrawable_epoch = uint64(
            epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2)
        total += int(v.effective_balance)
    state.slashings[epoch % spec.EPOCHS_PER_SLASHINGS_VECTOR] = \
        uint64(total)


@with_all_phases
@spec_state_test
def test_correlated_penalty(spec, state):
    n = len(state.validators)
    targets = list(range(0, n, max(1, n // 8)))[:8]
    _slash_validators_in_window(spec, state, targets)
    pre = [int(state.balances[i]) for i in targets]
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    for i, b in zip(targets, pre):
        assert int(state.balances[i]) <= b


@with_all_phases
@spec_state_test
def test_no_slashings_no_penalty(spec, state):
    pre = [int(b) for b in state.balances]
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    assert [int(b) for b in state.balances] == pre


@with_all_phases
@spec_state_test
def test_max_penalties(spec, state):
    """Slashing one third of the stake maximizes the correlation
    penalty: every slashed validator loses its whole effective
    balance (pre-bellatrix multiplier 1 -> x3 cap; bellatrix+ x3/x2
    reach the cap at a third)."""
    n = len(state.validators)
    slashed = list(range(n // 3))
    _slash_validators_in_window(spec, state, slashed)
    # slashings vector records a full third of the total balance
    total = int(spec.get_total_active_balance(state))
    epoch = int(spec.get_current_epoch(state))
    state.slashings[epoch % spec.EPOCHS_PER_SLASHINGS_VECTOR] = \
        uint64(total // 3)
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    for i in slashed:
        assert int(state.balances[i]) == 0 or \
            int(state.balances[i]) < int(
                state.validators[i].effective_balance)


@with_all_phases
@spec_state_test
def test_minimal_penalty(spec, state):
    """A single slashed validator among many: the proportional penalty
    rounds down to whole increments (possibly zero pre-cap)."""
    _slash_validators_in_window(spec, state, [4])
    pre = int(state.balances[4])
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    penalty = pre - int(state.balances[4])
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    # a lone slashing is proportionally small — far below the whole
    # effective balance
    assert penalty < int(state.validators[4].effective_balance)
    if not spec.is_post("electra"):
        # pre-electra the quotient math quantizes to whole increments
        # (electra's per-increment penalty rate does not)
        assert penalty % incr == 0


@with_all_phases
@spec_state_test
def test_slashings_out_of_window_untouched(spec, state):
    """Slashed validators whose withdrawable epoch is OUTSIDE the
    halfway window take no correlation penalty this epoch."""
    epoch = int(spec.get_current_epoch(state))
    v = state.validators[5]
    v.slashed = True
    # withdrawable far from epoch + EPOCHS_PER_SLASHINGS_VECTOR//2
    v.withdrawable_epoch = uint64(epoch + 3)
    state.slashings[epoch % spec.EPOCHS_PER_SLASHINGS_VECTOR] = \
        uint64(int(v.effective_balance))
    pre = int(state.balances[5])
    yield from run_epoch_processing_with(spec, state, "process_slashings")
    assert int(state.balances[5]) == pre
