"""Electra pending-queue epoch passes: process_pending_deposits
(finalization + churn gating, postponement for exited validators,
EIP-6110 bridge ordering) and process_pending_consolidations
(withdrawable-epoch gating, slashed-source skip, balance moves).

Reference batteries:
test/electra/epoch_processing/pending_deposits/ and
test_process_pending_consolidations.py.
"""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases_from
from ...test_infra.epoch_processing import run_epoch_processing_with
from ...test_infra.keys import pubkeys, privkeys
from ...test_infra.deposits import build_deposit_data


def _pending_deposit(spec, state, validator_index, amount, slot=0,
                     valid_sig=True):
    creds = b"\x01" + b"\x00" * 31
    data = build_deposit_data(spec, pubkeys[validator_index],
                              privkeys[validator_index], amount, creds,
                              signed=valid_sig)
    return spec.PendingDeposit(
        pubkey=pubkeys[validator_index],
        withdrawal_credentials=creds,
        amount=uint64(int(amount)),
        signature=data.signature,
        slot=uint64(int(slot)))


def _finalize_previous(spec, state) -> None:
    state.finalized_checkpoint.epoch = uint64(
        max(int(spec.get_current_epoch(state)) - 1, 0))


# ---------------------------------------------------------------------------
# pending deposits
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_top_up_applied(spec, state):
    _finalize_previous(spec, state)
    amount = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.pending_deposits.append(
        _pending_deposit(spec, state, 0, amount))
    pre = int(state.balances[0])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    assert int(state.balances[0]) == pre + amount
    assert len(state.pending_deposits) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_new_validator_valid_sig(spec, state):
    _finalize_previous(spec, state)
    fresh = len(state.validators)
    state.pending_deposits.append(_pending_deposit(
        spec, state, fresh, int(spec.MIN_ACTIVATION_BALANCE)))
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    assert len(state.validators) == fresh + 1
    assert state.validators[fresh].pubkey == pubkeys[fresh]


@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_new_validator_invalid_sig_dropped(spec, state):
    """A new-validator deposit with a bad signature is consumed without
    creating the validator (apply_pending_deposit's KeyValidate-style
    gate)."""
    _finalize_previous(spec, state)
    fresh = len(state.validators)
    dep = _pending_deposit(spec, state, fresh,
                           int(spec.MIN_ACTIVATION_BALANCE),
                           valid_sig=False)
    dep.signature = b"\x11" + b"\x00" * 95
    state.pending_deposits.append(dep)
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    assert len(state.validators) == fresh
    assert len(state.pending_deposits) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_not_finalized_waits(spec, state):
    """Deposits from unfinalized slots stay queued."""
    _finalize_previous(spec, state)
    far_slot = (int(spec.get_current_epoch(state)) + 10) \
        * int(spec.SLOTS_PER_EPOCH)
    state.pending_deposits.append(_pending_deposit(
        spec, state, 0, int(spec.EFFECTIVE_BALANCE_INCREMENT),
        slot=far_slot))
    pre = int(state.balances[0])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    assert int(state.balances[0]) == pre
    assert len(state.pending_deposits) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_churn_limit_carries_balance(spec, state):
    """Deposits beyond the activation churn wait; the unconsumed churn
    accumulates in deposit_balance_to_consume."""
    _finalize_previous(spec, state)
    churn = int(spec.get_activation_exit_churn_limit(state))
    big = churn + int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.pending_deposits.append(
        _pending_deposit(spec, state, 0, big))
    pre = int(state.balances[0])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    # too big for one epoch's churn: postponed, churn banked
    assert int(state.balances[0]) == pre
    assert len(state.pending_deposits) == 1
    assert int(state.deposit_balance_to_consume) == churn


@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_exited_validator_postponed(spec, state):
    """Deposits to an exited-but-not-withdrawn validator are postponed
    to the back of the queue."""
    _finalize_previous(spec, state)
    state.validators[0].exit_epoch = uint64(
        int(spec.get_current_epoch(state)) + 2)
    state.validators[0].withdrawable_epoch = uint64(
        int(spec.get_current_epoch(state)) + 10)
    state.pending_deposits.append(_pending_deposit(
        spec, state, 0, int(spec.EFFECTIVE_BALANCE_INCREMENT)))
    pre = int(state.balances[0])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    assert int(state.balances[0]) == pre
    assert len(state.pending_deposits) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_withdrawn_validator_applied_free(spec, state):
    """Deposits to a fully-withdrawable validator apply immediately,
    outside the churn accounting."""
    _finalize_previous(spec, state)
    state.validators[0].exit_epoch = uint64(0)
    state.validators[0].withdrawable_epoch = uint64(0)
    amount = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.pending_deposits.append(
        _pending_deposit(spec, state, 0, amount))
    pre = int(state.balances[0])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    assert int(state.balances[0]) == pre + amount
    assert len(state.pending_deposits) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_deposit_eth1_bridge_pending_blocks_requests(spec, state):
    """Deposit REQUESTS (slot > genesis) wait while eth1-bridge
    deposits are still being drained (eth1_deposit_index behind
    deposit_requests_start_index) — even once their slot is
    finalized."""
    from ...test_infra.blocks import next_epoch
    # finalize well past the deposit's slot so ONLY the bridge gate can
    # hold it back
    for _ in range(3):
        next_epoch(spec, state)
    _finalize_previous(spec, state)
    state.deposit_requests_start_index = uint64(
        int(state.eth1_deposit_index) + 5)
    state.pending_deposits.append(_pending_deposit(
        spec, state, 0, int(spec.EFFECTIVE_BALANCE_INCREMENT), slot=1))
    assert int(spec.compute_start_slot_at_epoch(
        state.finalized_checkpoint.epoch)) > 1
    yield from run_epoch_processing_with(
        spec, state, "process_pending_deposits")
    # the deposit stayed queued (earlier epoch passes may shift
    # balances via penalties, so the queue length is the witness)
    assert len(state.pending_deposits) == 1
    assert state.pending_deposits[0].slot == uint64(1)


# ---------------------------------------------------------------------------
# pending consolidations
# ---------------------------------------------------------------------------

def _queue_consolidation(spec, state, source, target,
                         withdrawable_delta=0):
    state.validators[source].withdrawable_epoch = uint64(
        int(spec.get_current_epoch(state)) + withdrawable_delta)
    state.validators[source].exit_epoch = uint64(
        int(spec.get_current_epoch(state)))
    state.pending_consolidations.append(spec.PendingConsolidation(
        source_index=source, target_index=target))


@with_all_phases_from("electra")
@spec_state_test
def test_pending_consolidation_moves_balance(spec, state):
    _queue_consolidation(spec, state, 0, 1)
    src_balance = int(state.balances[0])
    eff = int(state.validators[0].effective_balance)
    moved = min(src_balance, eff)
    pre_target = int(state.balances[1])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_consolidations")
    assert int(state.balances[1]) == pre_target + moved
    assert int(state.balances[0]) == src_balance - moved
    assert len(state.pending_consolidations) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_consolidation_not_withdrawable_waits(spec, state):
    _queue_consolidation(spec, state, 0, 1, withdrawable_delta=5)
    pre = (int(state.balances[0]), int(state.balances[1]))
    yield from run_epoch_processing_with(
        spec, state, "process_pending_consolidations")
    assert (int(state.balances[0]), int(state.balances[1])) == pre
    assert len(state.pending_consolidations) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_pending_consolidation_slashed_source_skipped(spec, state):
    """A slashed source forfeits the consolidation: the entry is
    consumed with NO balance move."""
    _queue_consolidation(spec, state, 0, 1)
    state.validators[0].slashed = True
    pre = (int(state.balances[0]), int(state.balances[1]))
    yield from run_epoch_processing_with(
        spec, state, "process_pending_consolidations")
    assert (int(state.balances[0]), int(state.balances[1])) == pre
    assert len(state.pending_consolidations) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_consolidation_source_balance_capped_by_effective(
        spec, state):
    """Only min(balance, effective_balance) moves; the excess stays
    with the source."""
    _queue_consolidation(spec, state, 0, 1)
    excess = int(spec.EFFECTIVE_BALANCE_INCREMENT) // 2
    state.balances[0] = uint64(
        int(state.validators[0].effective_balance) + excess)
    pre_target = int(state.balances[1])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_consolidations")
    assert int(state.balances[0]) == excess
    assert int(state.balances[1]) == pre_target + int(
        state.validators[0].effective_balance)


@with_all_phases_from("electra")
@spec_state_test
def test_pending_consolidation_chain_stops_at_unwithdrawable(spec, state):
    """Processing stops at the first not-yet-withdrawable source; later
    entries wait even if themselves ready."""
    _queue_consolidation(spec, state, 0, 1, withdrawable_delta=5)
    _queue_consolidation(spec, state, 2, 3)
    pre2 = int(state.balances[2])
    yield from run_epoch_processing_with(
        spec, state, "process_pending_consolidations")
    # the ready entry behind the blocked head did NOT process
    assert int(state.balances[2]) == pre2
    assert len(state.pending_consolidations) == 2
