"""Reset sub-pass epoch tests: eth1 votes, slashings vector slot, randao
mix rotation."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases
from ...test_infra.blocks import transition_to
from ...test_infra.epoch_processing import run_epoch_processing_with


@with_all_phases
@spec_state_test
def test_eth1_vote_reset_at_period_boundary(spec, state):
    # advance into the LAST epoch of an eth1 voting period
    period_slots = (int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD)
                    * int(spec.SLOTS_PER_EPOCH))
    transition_to(spec, state, period_slots - int(spec.SLOTS_PER_EPOCH))
    state.eth1_data_votes.append(spec.Eth1Data(deposit_count=7))
    yield from run_epoch_processing_with(
        spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == 0


@with_all_phases
@spec_state_test
def test_eth1_vote_no_reset_mid_period(spec, state):
    if int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD) == 1:
        return  # every epoch is a boundary under this preset
    state.eth1_data_votes.append(spec.Eth1Data(deposit_count=7))
    yield from run_epoch_processing_with(
        spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == 1


@with_all_phases
@spec_state_test
def test_slashings_reset(spec, state):
    next_epoch = int(spec.get_current_epoch(state)) + 1
    slot_index = next_epoch % int(spec.EPOCHS_PER_SLASHINGS_VECTOR)
    state.slashings[slot_index] = uint64(10**9)
    yield from run_epoch_processing_with(
        spec, state, "process_slashings_reset")
    assert int(state.slashings[slot_index]) == 0


@with_all_phases
@spec_state_test
def test_randao_mixes_reset(spec, state):
    current_epoch = int(spec.get_current_epoch(state))
    next_slot_index = (current_epoch + 1) % int(
        spec.EPOCHS_PER_HISTORICAL_VECTOR)
    yield from run_epoch_processing_with(
        spec, state, "process_randao_mixes_reset")
    assert bytes(state.randao_mixes[next_slot_index]) == bytes(
        spec.get_randao_mix(state, current_epoch))
