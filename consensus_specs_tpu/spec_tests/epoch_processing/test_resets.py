"""Reset sub-pass epoch tests: eth1 votes, slashings vector slot, randao
mix rotation."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases
from ...test_infra.blocks import transition_to
from ...test_infra.epoch_processing import run_epoch_processing_with


@with_all_phases
@spec_state_test
def test_eth1_vote_reset_at_period_boundary(spec, state):
    # advance into the LAST epoch of an eth1 voting period
    period_slots = (int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD)
                    * int(spec.SLOTS_PER_EPOCH))
    transition_to(spec, state, period_slots - int(spec.SLOTS_PER_EPOCH))
    state.eth1_data_votes.append(spec.Eth1Data(deposit_count=7))
    yield from run_epoch_processing_with(
        spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == 0


@with_all_phases
@spec_state_test
def test_eth1_vote_no_reset_mid_period(spec, state):
    if int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD) == 1:
        return  # every epoch is a boundary under this preset
    state.eth1_data_votes.append(spec.Eth1Data(deposit_count=7))
    yield from run_epoch_processing_with(
        spec, state, "process_eth1_data_reset")
    assert len(state.eth1_data_votes) == 1


@with_all_phases
@spec_state_test
def test_slashings_reset(spec, state):
    next_epoch = int(spec.get_current_epoch(state)) + 1
    slot_index = next_epoch % int(spec.EPOCHS_PER_SLASHINGS_VECTOR)
    state.slashings[slot_index] = uint64(10**9)
    yield from run_epoch_processing_with(
        spec, state, "process_slashings_reset")
    assert int(state.slashings[slot_index]) == 0


@with_all_phases
@spec_state_test
def test_randao_mixes_reset(spec, state):
    current_epoch = int(spec.get_current_epoch(state))
    next_slot_index = (current_epoch + 1) % int(
        spec.EPOCHS_PER_HISTORICAL_VECTOR)
    yield from run_epoch_processing_with(
        spec, state, "process_randao_mixes_reset")
    assert bytes(state.randao_mixes[next_slot_index]) == bytes(
        spec.get_randao_mix(state, current_epoch))


@with_all_phases
@spec_state_test
def test_historical_accumulator_update_at_boundary(spec, state):
    """Crossing a SLOTS_PER_HISTORICAL_ROOT boundary appends one
    accumulator entry (roots pre-capella, summaries after)."""
    target = int(spec.SLOTS_PER_HISTORICAL_ROOT) - 1
    transition_to(spec, state, uint64(target))
    pass_name = ("process_historical_summaries_update"
                 if spec.is_post("capella")
                 else "process_historical_roots_update")
    pre_hist = len(state.historical_roots)
    pre_summ = len(state.historical_summaries) \
        if spec.is_post("capella") else 0
    yield from run_epoch_processing_with(spec, state, pass_name)
    if spec.is_post("capella"):
        assert len(state.historical_summaries) == pre_summ + 1
    else:
        assert len(state.historical_roots) == pre_hist + 1


@with_all_phases
@spec_state_test
def test_historical_accumulator_no_update_mid_period(spec, state):
    transition_to(spec, state, uint64(int(spec.SLOTS_PER_EPOCH) - 1))
    pass_name = ("process_historical_summaries_update"
                 if spec.is_post("capella")
                 else "process_historical_roots_update")
    pre_hist = len(state.historical_roots)
    pre_summ = len(state.historical_summaries) \
        if spec.is_post("capella") else 0
    yield from run_epoch_processing_with(spec, state, pass_name)
    if spec.is_post("capella"):
        assert len(state.historical_summaries) == pre_summ
    else:
        assert len(state.historical_roots) == pre_hist


@with_all_phases
@spec_state_test
def test_slashings_reset_only_next_slot_cleared(spec, state):
    """The reset zeroes exactly the NEXT epoch's slashings slot,
    leaving the rest of the ring intact."""
    vec = int(spec.EPOCHS_PER_SLASHINGS_VECTOR)
    for i in range(vec):
        state.slashings[i] = uint64(1000 + i)
    cur = int(spec.get_current_epoch(state))
    nxt = (cur + 1) % vec
    yield from run_epoch_processing_with(
        spec, state, "process_slashings_reset")
    for i in range(vec):
        expect = 0 if i == nxt else 1000 + i
        assert int(state.slashings[i]) == expect, i


@with_all_phases
@spec_state_test
def test_randao_mixes_carry_forward(spec, state):
    """The next epoch's randao slot inherits the current mix."""
    vec = int(spec.EPOCHS_PER_HISTORICAL_VECTOR)
    cur = int(spec.get_current_epoch(state))
    cur_mix = bytes(state.randao_mixes[cur % vec])
    yield from run_epoch_processing_with(
        spec, state, "process_randao_mixes_reset")
    assert bytes(state.randao_mixes[(cur + 1) % vec]) == cur_mix


from ...test_infra.context import with_all_phases_from  # noqa: E402


@with_all_phases_from("altair")
@spec_state_test
def test_participation_flag_rotation(spec, state):
    """Epoch rotation moves current flags to previous and zeroes
    current."""
    n = len(state.validators)
    state.current_epoch_participation = [0b101] * n
    state.previous_epoch_participation = [0b010] * n
    yield from run_epoch_processing_with(
        spec, state, "process_participation_flag_updates")
    assert all(int(f) == 0b101
               for f in state.previous_epoch_participation)
    assert all(int(f) == 0
               for f in state.current_epoch_participation)


@with_all_phases_from("altair")
@spec_state_test
def test_sync_committee_rotation_at_period_boundary(spec, state):
    """At an EPOCHS_PER_SYNC_COMMITTEE_PERIOD boundary the next
    committee shifts in and a fresh one is computed."""
    period_slots = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD) * \
        int(spec.SLOTS_PER_EPOCH)
    transition_to(spec, state, uint64(period_slots - 1))
    pre_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    assert state.current_sync_committee == pre_next
