"""apply_pending_deposit battery (electra; reference
test/electra/epoch_processing/pending_deposits/
test_apply_pending_deposit.py, 25 defs): every credential shape,
signature outcome, and top-up interaction of a single queued deposit
draining through process_pending_deposits."""
from ...ssz import Bytes32, uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, always_bls)
from ...test_infra.deposits import build_deposit_data
from ...test_infra.epoch_processing import run_epoch_processing_to
from ...test_infra.keys import pubkeys, privkeys

# a positive non-infinity G1 x-coordinate outside the subgroup
_PUBKEY_NOT_IN_SUBGROUP = bytes.fromhex(
    "8123456789abcdef0123456789abcdef0123456789abcdef"
    "0123456789abcdef0123456789abcdef0123456789abcdef")
_PUBKEY_NOT_DECOMPRESSIBLE = bytes.fromhex(
    "8123456789abcdef0123456789abcdef0123456789abcdef"
    "0123456789abcdef0123456789abcdef0123456789abcde0")


def _bls_creds(spec, pubkey):
    return bytes(spec.BLS_WITHDRAWAL_PREFIX) + \
        bytes(spec.hash(pubkey))[1:]


def _eth1_creds(spec):
    return bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 \
        + b"\x42" * 20


def _compounding_creds(spec):
    return bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11 \
        + b"\x42" * 20


def _pending_deposit_for(spec, key_index, amount, creds=None,
                         signed=True, pubkey_override=None, slot=0):
    pubkey = pubkeys[key_index] if pubkey_override is None \
        else pubkey_override
    if creds is None:
        creds = _bls_creds(spec, pubkey)
    data = build_deposit_data(spec, pubkey, privkeys[key_index],
                              amount, creds, signed=signed)
    return spec.PendingDeposit(
        pubkey=pubkey, withdrawal_credentials=Bytes32(creds),
        amount=uint64(int(amount)), signature=data.signature,
        slot=uint64(slot))


def _run_apply(spec, state, pending_deposit, validator_index,
               effective=True):
    """Queue one deposit and drain it through
    process_pending_deposits (reference run_pending_deposit_applying)."""
    state.deposit_requests_start_index = state.eth1_deposit_index
    if int(pending_deposit.amount) > int(
            spec.get_activation_exit_churn_limit(state)):
        state.deposit_balance_to_consume = uint64(
            int(pending_deposit.amount)
            - int(spec.get_activation_exit_churn_limit(state)))
    state.pending_deposits.append(pending_deposit)
    run_epoch_processing_to(spec, state,
                            "process_justification_and_finalization")
    pre_count = len(state.validators)
    is_top_up = validator_index < pre_count
    pre_balance = int(state.balances[validator_index]) if is_top_up else 0
    yield "pre", state.copy()
    spec.process_pending_deposits(state)
    yield "post", state
    assert len(state.pending_deposits) == 0
    if effective:
        if is_top_up:
            assert len(state.validators) == pre_count
            assert int(state.balances[validator_index]) == \
                pre_balance + int(pending_deposit.amount)
        else:
            assert len(state.validators) == pre_count + 1
            assert int(state.balances[validator_index]) == \
                int(pending_deposit.amount)
    else:
        assert len(state.validators) == pre_count
        if is_top_up:
            assert int(state.balances[validator_index]) == pre_balance


# --- new-validator deposits: amounts ---------------------------------------

@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_under_min_activation(spec, state):
    index = len(state.validators)
    amount = int(spec.MIN_ACTIVATION_BALANCE) - 1
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_min_activation(spec, state):
    index = len(state.validators)
    pd = _pending_deposit_for(spec, index,
                              int(spec.MIN_ACTIVATION_BALANCE),
                              signed=True)
    yield from _run_apply(spec, state, pd, index)
    assert int(state.validators[index].effective_balance) == \
        int(spec.MIN_ACTIVATION_BALANCE)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_over_min_activation(spec, state):
    index = len(state.validators)
    amount = int(spec.MIN_ACTIVATION_BALANCE) \
        + int(spec.EFFECTIVE_BALANCE_INCREMENT)
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)
    # 0x00 creds: effective balance capped at MIN_ACTIVATION_BALANCE
    assert int(state.validators[index].effective_balance) == \
        int(spec.MIN_ACTIVATION_BALANCE)


# --- credential shapes -----------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_eth1_withdrawal_credentials(spec, state):
    index = len(state.validators)
    pd = _pending_deposit_for(spec, index,
                              int(spec.MIN_ACTIVATION_BALANCE),
                              creds=_eth1_creds(spec), signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_compounding_withdrawal_credentials_under_max(
        spec, state):
    index = len(state.validators)
    amount = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA) \
        - int(spec.EFFECTIVE_BALANCE_INCREMENT)
    pd = _pending_deposit_for(spec, index, amount,
                              creds=_compounding_creds(spec),
                              signed=True)
    yield from _run_apply(spec, state, pd, index)
    assert int(state.validators[index].effective_balance) == amount


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_compounding_withdrawal_credentials_max(
        spec, state):
    index = len(state.validators)
    amount = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    pd = _pending_deposit_for(spec, index, amount,
                              creds=_compounding_creds(spec),
                              signed=True)
    yield from _run_apply(spec, state, pd, index)
    assert int(state.validators[index].effective_balance) == amount


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_compounding_withdrawal_credentials_over_max(
        spec, state):
    index = len(state.validators)
    amount = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA) \
        + int(spec.EFFECTIVE_BALANCE_INCREMENT)
    pd = _pending_deposit_for(spec, index, amount,
                              creds=_compounding_creds(spec),
                              signed=True)
    yield from _run_apply(spec, state, pd, index)
    # balance holds the full amount; EB caps at the compounding max
    assert int(state.validators[index].effective_balance) == \
        int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_non_versioned_withdrawal_credentials(
        spec, state):
    index = len(state.validators)
    creds = b"\xff" + b"\x02" * 31  # unknown prefix: still accepted
    pd = _pending_deposit_for(spec, index,
                              int(spec.MIN_ACTIVATION_BALANCE),
                              creds=creds, signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_non_versioned_withdrawal_credentials_over_min_activation(
        spec, state):
    index = len(state.validators)
    creds = b"\xff" + b"\x02" * 31
    amount = int(spec.MIN_ACTIVATION_BALANCE) \
        + int(spec.EFFECTIVE_BALANCE_INCREMENT)
    pd = _pending_deposit_for(spec, index, amount, creds=creds,
                              signed=True)
    yield from _run_apply(spec, state, pd, index)


# --- signature / pubkey validation ----------------------------------------

@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_incorrect_sig_new_deposit(spec, state):
    index = len(state.validators)
    pd = _pending_deposit_for(spec, index,
                              int(spec.MIN_ACTIVATION_BALANCE),
                              signed=False)
    pd.signature = b"\x11" + b"\x00" * 95
    yield from _run_apply(spec, state, pd, index, effective=False)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_key_validate_invalid_subgroup(spec, state):
    index = len(state.validators)
    pd = _pending_deposit_for(
        spec, index, int(spec.MIN_ACTIVATION_BALANCE), signed=False,
        pubkey_override=_PUBKEY_NOT_IN_SUBGROUP)
    yield from _run_apply(spec, state, pd, index, effective=False)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_key_validate_invalid_decompression(
        spec, state):
    index = len(state.validators)
    pd = _pending_deposit_for(
        spec, index, int(spec.MIN_ACTIVATION_BALANCE), signed=False,
        pubkey_override=_PUBKEY_NOT_DECOMPRESSIBLE)
    yield from _run_apply(spec, state, pd, index, effective=False)


# --- top-ups ---------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_top_up__min_activation_balance(spec,
                                                              state):
    index = 0
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_top_up__max_effective_balance_compounding(
        spec, state):
    from ...test_infra.withdrawals import (
        set_compounding_withdrawal_credentials)
    index = 0
    set_compounding_withdrawal_credentials(spec, state, index)
    state.validators[index].effective_balance = \
        spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    state.balances[index] = spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_top_up__less_effective_balance(spec,
                                                              state):
    index = 0
    state.validators[index].effective_balance = uint64(
        int(spec.MIN_ACTIVATION_BALANCE)
        - int(spec.EFFECTIVE_BALANCE_INCREMENT))
    state.balances[index] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE)
        - int(spec.EFFECTIVE_BALANCE_INCREMENT))
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_top_up__zero_balance(spec, state):
    index = 0
    state.validators[index].effective_balance = 0
    state.balances[index] = 0
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_incorrect_sig_top_up(spec, state):
    """Top-ups skip signature verification entirely."""
    index = 0
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    pd = _pending_deposit_for(spec, index, amount, signed=False)
    pd.signature = b"\x11" + b"\x00" * 95
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_incorrect_withdrawal_credentials_top_up(
        spec, state):
    """A top-up with mismatched credentials still credits the balance
    (credentials are pinned at first deposit)."""
    index = 0
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    creds = bytes(spec.BLS_WITHDRAWAL_PREFIX) \
        + bytes(spec.hash(b"\x03" * 48))[1:]
    pd = _pending_deposit_for(spec, index, amount, creds=creds,
                              signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_success_top_up_to_withdrawn_validator(
        spec, state):
    from ...test_infra.withdrawals import (
        prepare_fully_withdrawable_validator)
    index = 0
    prepare_fully_withdrawable_validator(spec, state, index, balance=0)
    state.validators[index].effective_balance = 0
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)


# --- fork-version signing --------------------------------------------------

def _pending_deposit_with_version(spec, key_index, amount, version):
    from ...utils import bls as _bls
    pubkey = pubkeys[key_index]
    creds = _bls_creds(spec, pubkey)
    deposit_message = spec.DepositMessage(
        pubkey=pubkey, withdrawal_credentials=Bytes32(creds),
        amount=uint64(amount))
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT, version, Bytes32())
    signature = _bls.Sign(privkeys[key_index],
                          spec.compute_signing_root(deposit_message,
                                                    domain))
    return spec.PendingDeposit(
        pubkey=pubkey, withdrawal_credentials=Bytes32(creds),
        amount=uint64(amount), signature=signature, slot=uint64(0))


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_effective_deposit_with_genesis_fork_version(
        spec, state):
    index = len(state.validators)
    version = bytes.fromhex(
        str(spec.config.GENESIS_FORK_VERSION)[2:])
    pd = _pending_deposit_with_version(
        spec, index, int(spec.MIN_ACTIVATION_BALANCE), version)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_ineffective_deposit_with_bad_fork_version(
        spec, state):
    index = len(state.validators)
    pd = _pending_deposit_with_version(
        spec, index, int(spec.MIN_ACTIVATION_BALANCE), b"\xaa\xbb\xcc\xdd")
    yield from _run_apply(spec, state, pd, index, effective=False)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_ineffective_deposit_with_current_fork_version(
        spec, state):
    """Deposits must sign over the GENESIS fork version — the current
    fork's version does not verify."""
    index = len(state.validators)
    version = bytes.fromhex(
        str(getattr(spec.config, f"{spec.fork.upper()}_FORK_VERSION"))[2:])
    pd = _pending_deposit_with_version(
        spec, index, int(spec.MIN_ACTIVATION_BALANCE), version)
    yield from _run_apply(spec, state, pd, index, effective=False)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_correct_sig_but_forked_state(spec, state):
    """Deposit domains pin GENESIS_FORK_VERSION: a mangled state fork
    version changes nothing."""
    index = len(state.validators)
    state.fork.current_version = b"\x12\x34\xab\xcd"
    pd = _pending_deposit_for(spec, index,
                              int(spec.MIN_ACTIVATION_BALANCE),
                              signed=True)
    yield from _run_apply(spec, state, pd, index)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_top_up__min_activation_balance_compounding(
        spec, state):
    """Top-up to an at-cap 0x02 validator with a 32-ETH max: balance
    grows, effective balance stays pinned."""
    index = 0
    creds = _compounding_creds(spec)
    state.validators[index].withdrawal_credentials = Bytes32(creds)
    state.validators[index].effective_balance = \
        spec.MIN_ACTIVATION_BALANCE
    state.balances[index] = spec.MIN_ACTIVATION_BALANCE
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    pd = _pending_deposit_for(spec, index, amount, signed=True)
    yield from _run_apply(spec, state, pd, index)
    assert int(state.balances[index]) == \
        int(spec.MIN_ACTIVATION_BALANCE) + amount
    assert int(state.validators[index].effective_balance) == \
        int(spec.MIN_ACTIVATION_BALANCE)


@with_all_phases_from("electra")
@spec_state_test
@always_bls
def test_apply_pending_deposit_with_previous_fork_version(spec, state):
    """Signed over state.fork.previous_version: ineffective — deposits
    only verify over GENESIS_FORK_VERSION (this WAS effective in
    altair's process_deposit)."""
    assert bytes(state.fork.previous_version) \
        != bytes(state.fork.current_version)
    index = len(state.validators)
    pd = _pending_deposit_with_version(
        spec, index, int(spec.MIN_ACTIVATION_BALANCE),
        bytes(state.fork.previous_version))
    yield from _run_apply(spec, state, pd, index, effective=False)
