"""process_sync_committee_updates epoch battery (altair+; reference
test/altair/epoch_processing/test_process_sync_committee_updates.py,
5 defs): committee rotation at period boundaries, no-ops elsewhere,
and rotation under mixed balances."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, with_presets,
    with_custom_state, misc_balances, default_activation_threshold)
from ...test_infra.blocks import transition_to
from ...test_infra.epoch_processing import run_epoch_processing_with


def _to_last_epoch_of_period(spec, state, periods=1) -> None:
    """Advance so the NEXT epoch boundary is a sync-committee period
    boundary."""
    period_epochs = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    cur = int(spec.get_current_epoch(state))
    target_epoch = ((cur // period_epochs) + periods) * period_epochs - 1
    transition_to(
        spec, state,
        uint64(target_epoch * int(spec.SLOTS_PER_EPOCH)))


def _run_rotation(spec, state):
    pre_current = state.current_sync_committee.copy()
    pre_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    # rotated: next became current, a fresh next was computed
    assert state.current_sync_committee == pre_next
    assert state.next_sync_committee != pre_next
    return pre_current


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="period fast-forward too slow")
@spec_state_test
def test_sync_committees_progress_genesis(spec, state):
    assert int(spec.get_current_epoch(state)) == 0
    _to_last_epoch_of_period(spec, state)
    yield from _run_rotation(spec, state)


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="period fast-forward too slow")
@spec_state_test
def test_sync_committees_progress_not_genesis(spec, state):
    # start one epoch in, still rotating at the same boundary
    transition_to(spec, state, uint64(int(spec.SLOTS_PER_EPOCH)))
    _to_last_epoch_of_period(spec, state)
    yield from _run_rotation(spec, state)


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="period fast-forward too slow")
@with_custom_state(misc_balances, default_activation_threshold)
@spec_state_test
def test_sync_committees_progress_misc_balances_genesis(spec, state):
    _to_last_epoch_of_period(spec, state)
    yield from _run_rotation(spec, state)


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="period fast-forward too slow")
@with_custom_state(misc_balances, default_activation_threshold)
@spec_state_test
def test_sync_committees_progress_misc_balances_not_genesis(spec, state):
    transition_to(spec, state, uint64(int(spec.SLOTS_PER_EPOCH)))
    _to_last_epoch_of_period(spec, state)
    yield from _run_rotation(spec, state)


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="period fast-forward too slow")
@spec_state_test
def test_sync_committees_no_progress_not_at_period_boundary(spec, state):
    period_epochs = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    assert period_epochs > 1
    # an ordinary epoch boundary inside the period
    pre_current = state.current_sync_committee.copy()
    pre_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    assert state.current_sync_committee == pre_current
    assert state.next_sync_committee == pre_next
