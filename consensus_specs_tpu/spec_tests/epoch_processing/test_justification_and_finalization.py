"""process_justification_and_finalization epoch tests."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, never_bls)
from ...test_infra.blocks import next_epoch
from ...test_infra.epoch_processing import run_epoch_processing_with


def _set_full_participation(spec, state):
    """Mark every active validator as a previous+current target attester."""
    if spec.is_post("altair"):
        full = 0
        for flag in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            full = spec.add_flag(full, flag)
        n = len(state.validators)
        state.previous_epoch_participation = [full] * n
        state.current_epoch_participation = [full] * n
    else:
        from ...test_infra.attestations import next_epoch_with_attestations
        # real pending attestations are required pre-altair
        _, _ = next_epoch_with_attestations(spec, state, True, True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_participation_justifies(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    _set_full_participation(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")
    assert int(state.current_justified_checkpoint.epoch) > 0


@with_all_phases
@spec_state_test
def test_no_participation_no_justification(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    pre_justified = state.current_justified_checkpoint.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")
    assert state.current_justified_checkpoint == pre_justified


@with_all_phases
@spec_state_test
def test_genesis_epoch_no_op(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    pre_bits = state.justification_bits.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")
    assert state.justification_bits == pre_bits
