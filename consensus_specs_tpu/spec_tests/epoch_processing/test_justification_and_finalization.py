"""process_justification_and_finalization epoch tests."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, never_bls)
from ...test_infra.blocks import next_epoch
from ...test_infra.epoch_processing import run_epoch_processing_with


def _set_full_participation(spec, state):
    """Mark every active validator as a previous+current target attester."""
    if spec.is_post("altair"):
        full = 0
        for flag in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            full = spec.add_flag(full, flag)
        n = len(state.validators)
        state.previous_epoch_participation = [full] * n
        state.current_epoch_participation = [full] * n
    else:
        from ...test_infra.attestations import next_epoch_with_attestations
        # real pending attestations are required pre-altair
        _, _ = next_epoch_with_attestations(spec, state, True, True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_participation_justifies(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    _set_full_participation(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")
    assert int(state.current_justified_checkpoint.epoch) > 0


@with_all_phases
@spec_state_test
def test_no_participation_no_justification(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    pre_justified = state.current_justified_checkpoint.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")
    assert state.current_justified_checkpoint == pre_justified


@with_all_phases
@spec_state_test
def test_genesis_epoch_no_op(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    pre_bits = state.justification_bits.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_justification_and_finalization")
    assert state.justification_bits == pre_bits


# ---------------------------------------------------------------------------
# the four FFG finality rules x {sufficient, insufficient} support
# (reference test_process_justification_and_finalization.py matrix)
# ---------------------------------------------------------------------------

from ...test_infra.finality_rules import (
    finalize_on_234, finalize_on_23, finalize_on_123, finalize_on_12)


@with_all_phases
@spec_state_test
@never_bls
def test_234_ok_support(spec, state):
    yield from finalize_on_234(spec, state, 5, sufficient_support=True)


@with_all_phases
@spec_state_test
@never_bls
def test_234_poor_support(spec, state):
    yield from finalize_on_234(spec, state, 5, sufficient_support=False)


@with_all_phases
@spec_state_test
@never_bls
def test_23_ok_support(spec, state):
    yield from finalize_on_23(spec, state, 4, sufficient_support=True)


@with_all_phases
@spec_state_test
@never_bls
def test_23_poor_support(spec, state):
    yield from finalize_on_23(spec, state, 4, sufficient_support=False)


@with_all_phases
@spec_state_test
@never_bls
def test_123_ok_support(spec, state):
    yield from finalize_on_123(spec, state, 6, sufficient_support=True)


@with_all_phases
@spec_state_test
@never_bls
def test_123_poor_support(spec, state):
    yield from finalize_on_123(spec, state, 6, sufficient_support=False)


@with_all_phases
@spec_state_test
@never_bls
def test_12_ok_support(spec, state):
    yield from finalize_on_12(spec, state, 3, sufficient_support=True)


@with_all_phases
@spec_state_test
@never_bls
def test_12_ok_support_messed_target(spec, state):
    yield from finalize_on_12(spec, state, 3, sufficient_support=True,
                              messed_up_target=True)


@with_all_phases
@spec_state_test
@never_bls
def test_12_poor_support(spec, state):
    yield from finalize_on_12(spec, state, 3, sufficient_support=False)
