"""Epoch-processing tests for the altair-family participation machinery:
inactivity updates, participation-flag rotation, sync-committee rotation
(reference: test/altair/epoch_processing/*)."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, never_bls)
from ...test_infra.blocks import next_epoch, transition_to
from ...test_infra.epoch_processing import run_epoch_processing_with


def _full_flags(spec):
    flags = 0
    for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        flags = spec.add_flag(flags, i)
    return flags


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_inactivity_scores_genesis_noop(spec, state):
    """In-leak score bumps don't apply during the genesis epoch."""
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    next_epoch(spec, state)
    yield from run_epoch_processing_with(spec, state,
                                         "process_inactivity_updates")


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_inactivity_scores_leaking(spec, state):
    """Drive the chain into a leak (no finality for
    MIN_EPOCHS_TO_INACTIVITY_PENALTY+) with empty participation; scores
    must rise by INACTIVITY_SCORE_BIAS."""
    target = (int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3) * \
        int(spec.SLOTS_PER_EPOCH)
    transition_to(spec, state, uint64(target))
    state.previous_epoch_participation = [0] * len(state.validators)
    state.current_epoch_participation = [0] * len(state.validators)
    assert spec.is_in_inactivity_leak(state)
    pre_scores = list(state.inactivity_scores)
    yield from run_epoch_processing_with(spec, state,
                                         "process_inactivity_updates")
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    for i, v in enumerate(state.validators):
        if spec.is_active_validator(v, spec.get_previous_epoch(state)):
            assert int(state.inactivity_scores[i]) == \
                int(pre_scores[i]) + bias


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_inactivity_scores_recovery(spec, state):
    """Full participation with finality: scores decay by the recovery
    rate."""
    next_epoch(spec, state)
    next_epoch(spec, state)
    n = len(state.validators)
    state.inactivity_scores = [100] * n
    state.previous_epoch_participation = [_full_flags(spec)] * n
    # finality close enough: not leaking
    state.finalized_checkpoint.epoch = uint64(
        max(int(spec.get_current_epoch(state)) - 2, 0))
    assert not spec.is_in_inactivity_leak(state)
    yield from run_epoch_processing_with(spec, state,
                                         "process_inactivity_updates")
    # participating: -1; not leaking: a further -RECOVERY_RATE
    rate = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    for i, v in enumerate(state.validators):
        if spec.is_active_validator(v, spec.get_previous_epoch(state)):
            assert int(state.inactivity_scores[i]) == 100 - 1 - rate


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_participation_flag_rotation(spec, state):
    next_epoch(spec, state)
    n = len(state.validators)
    cur = [_full_flags(spec)] * n
    state.current_epoch_participation = cur
    state.previous_epoch_participation = [1] * n
    yield from run_epoch_processing_with(
        spec, state, "process_participation_flag_updates")
    assert list(state.previous_epoch_participation) == cur
    assert list(state.current_epoch_participation) == [0] * n


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_sync_committee_rotation_at_period_boundary(spec, state):
    """At an EPOCHS_PER_SYNC_COMMITTEE_PERIOD boundary the next
    committee shifts in and a fresh one is computed."""
    period_slots = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD) * \
        int(spec.SLOTS_PER_EPOCH)
    transition_to(spec, state, uint64(period_slots - 1))
    expected_current = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    assert state.current_sync_committee == expected_current


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_sync_committee_no_rotation_mid_period(spec, state):
    next_epoch(spec, state)
    pre_cur = state.current_sync_committee.copy()
    pre_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    assert state.current_sync_committee == pre_cur
    assert state.next_sync_committee == pre_next


@with_all_phases_from("capella")
@spec_state_test
@never_bls
def test_historical_summaries_update(spec, state):
    """At a SLOTS_PER_HISTORICAL_ROOT boundary a summary is appended."""
    boundary = int(spec.SLOTS_PER_HISTORICAL_ROOT)
    transition_to(spec, state, uint64(boundary - 1))
    pre_len = len(state.historical_summaries)
    yield from run_epoch_processing_with(
        spec, state, "process_historical_summaries_update")
    assert len(state.historical_summaries) == pre_len + 1


@with_all_phases_from("electra")
@spec_state_test
@never_bls
def test_pending_deposit_applied(spec, state):
    """A pending deposit for an existing validator tops up its
    balance."""
    from ...test_infra.epoch_processing import run_epoch_processing_to
    next_epoch(spec, state)
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    state.pending_deposits = [spec.PendingDeposit(
        pubkey=state.validators[0].pubkey,
        withdrawal_credentials=state.validators[0].withdrawal_credentials,
        amount=amount,
        signature=b"\x11" + b"\x00" * 95,
        slot=spec.GENESIS_SLOT)]
    # run the earlier passes first so the balance snapshot isolates this
    # pass (rewards/penalties also move balances)
    run_epoch_processing_to(spec, state, "process_pending_deposits")
    pre_balance = int(state.balances[0])
    yield "pre", state.copy()
    spec.process_pending_deposits(state)
    yield "post", state
    assert int(state.balances[0]) == pre_balance + int(amount)
    assert len(state.pending_deposits) == 0


@with_all_phases_from("electra")
@spec_state_test
@never_bls
def test_pending_consolidation_applied(spec, state):
    """A withdrawable pending consolidation moves the source balance to
    the target."""
    from ...test_infra.withdrawals import (
        set_eth1_withdrawal_credentials,
        set_compounding_withdrawal_credentials)
    next_epoch(spec, state)
    source, target = 0, 1
    set_eth1_withdrawal_credentials(spec, state, source)
    set_compounding_withdrawal_credentials(spec, state, target)
    cur = spec.get_current_epoch(state)
    state.validators[source].exit_epoch = uint64(max(int(cur) - 1, 0))
    state.validators[source].withdrawable_epoch = cur
    state.pending_consolidations = [spec.PendingConsolidation(
        source_index=source, target_index=target)]
    from ...test_infra.epoch_processing import run_epoch_processing_to
    run_epoch_processing_to(spec, state,
                            "process_pending_consolidations")
    pre_source = int(state.balances[source])
    pre_target = int(state.balances[target])
    yield "pre", state.copy()
    spec.process_pending_consolidations(state)
    yield "post", state
    assert len(state.pending_consolidations) == 0
    assert int(state.balances[source]) == 0
    assert int(state.balances[target]) == pre_source + pre_target


# ---------------------------------------------------------------------------
# flag-rotation matrix (reference altair
# test_process_participation_flag_updates.py, 12 defs)
# ---------------------------------------------------------------------------

import random as _random  # noqa: E402


def _run_flag_rotation(spec, state, prev_fn, cur_fn):
    n = len(state.validators)
    state.previous_epoch_participation = [prev_fn(i) for i in range(n)]
    state.current_epoch_participation = [cur_fn(i) for i in range(n)]
    staged_current = [int(p) for p in state.current_epoch_participation]
    yield from run_epoch_processing_with(
        spec, state, "process_participation_flag_updates")
    # rotation: current -> previous, current zeroed
    assert [int(p) for p in state.previous_epoch_participation] \
        == staged_current
    assert all(int(p) == 0 for p in state.current_epoch_participation)


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_all_zeroed(spec, state):
    yield from _run_flag_rotation(spec, state, lambda i: 0, lambda i: 0)


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_filled(spec, state):
    yield from _run_flag_rotation(spec, state, lambda i: 0b111,
                                  lambda i: 0b111)


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_previous_filled(spec, state):
    yield from _run_flag_rotation(spec, state, lambda i: 0b111,
                                  lambda i: 0)


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_current_filled(spec, state):
    yield from _run_flag_rotation(spec, state, lambda i: 0,
                                  lambda i: 0b111)


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_previous_epoch_zeroed(spec, state):
    rng = _random.Random(4041)
    yield from _run_flag_rotation(
        spec, state, lambda i: 0,
        lambda i: rng.randrange(0, 0b1000))


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_current_epoch_zeroed(spec, state):
    rng = _random.Random(4042)
    yield from _run_flag_rotation(
        spec, state, lambda i: rng.randrange(0, 0b1000),
        lambda i: 0)


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_random_0(spec, state):
    rng = _random.Random(1010)
    yield from _run_flag_rotation(
        spec, state, lambda i: rng.randrange(0, 0b1000),
        lambda i: rng.randrange(0, 0b1000))


@with_all_phases_from("altair")
@spec_state_test
def test_flag_rotation_large_random(spec, state):
    rng = _random.Random(2020)
    yield from _run_flag_rotation(
        spec, state, lambda i: rng.getrandbits(8) & 0b111,
        lambda i: rng.getrandbits(8) & 0b111)
