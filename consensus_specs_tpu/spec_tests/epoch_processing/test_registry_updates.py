"""process_registry_updates epoch tests (eligibility, ejection,
activation queue)."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases, with_all_phases_from)
from ...test_infra.epoch_processing import run_epoch_processing_with
from ...test_infra.genesis import build_mock_validator


@with_all_phases
@spec_state_test
def test_new_validator_becomes_eligible(spec, state):
    fresh = build_mock_validator(
        spec, len(state.validators), spec.MAX_EFFECTIVE_BALANCE)
    state.validators.append(fresh)
    state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    if spec.is_post("altair"):
        state.previous_epoch_participation.append(0)
        state.current_epoch_participation.append(0)
        state.inactivity_scores.append(0)
    index = len(state.validators) - 1
    assert state.validators[index].activation_eligibility_epoch == \
        spec.FAR_FUTURE_EPOCH
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch != \
        spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_low_balance_validator_ejected(spec, state):
    index = 2
    state.validators[index].effective_balance = uint64(
        spec.config.EJECTION_BALANCE)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_eligible_validator_gets_activated(spec, state):
    index = 3
    v = state.validators[index]
    v.activation_epoch = spec.FAR_FUTURE_EPOCH
    v.activation_eligibility_epoch = uint64(0)
    state.finalized_checkpoint.epoch = uint64(
        int(spec.get_current_epoch(state)))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_epoch != \
        spec.FAR_FUTURE_EPOCH


def _queue_validator(spec, state, index, eligibility_epoch):
    """Put validator `index` into the activation queue with a chosen
    eligibility epoch."""
    v = state.validators[index]
    v.activation_epoch = spec.FAR_FUTURE_EPOCH
    v.activation_eligibility_epoch = uint64(int(eligibility_epoch))


def _finalize_now(spec, state) -> None:
    # finalize the PREVIOUS epoch: finality can never lead the head
    # (get_finality_delay = previous_epoch - finalized_epoch underflows
    # otherwise)
    state.finalized_checkpoint.epoch = uint64(
        max(int(spec.get_current_epoch(state)) - 1, 0))


@with_all_phases
@spec_state_test
def test_activation_queue_no_activation_no_finality(spec, state):
    """Eligible validators stay queued while finality lags behind
    their eligibility epoch."""
    from ...test_infra.blocks import next_epoch
    next_epoch(spec, state)
    index = 3
    _queue_validator(spec, state, index,
                     int(spec.get_current_epoch(state)) + 10)
    # finalized checkpoint stays at genesis: eligibility not finalized
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_epoch == \
        spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_sorting(spec, state):
    """Dequeue order follows (eligibility epoch, index); the churn
    limit truncates the tail pre-electra (electra activates everyone
    eligible — beacon-chain.md:825)."""
    from ...test_infra.blocks import next_epoch
    churn = int(spec.get_validator_churn_limit(state)) \
        if not spec.is_post("electra") else None
    mock_count = (churn + 2) if churn is not None else 6
    mock_count = min(mock_count, len(state.validators) - 1)
    # eligibility epochs must be <= the finalized epoch to dequeue
    for _ in range(mock_count + 1):
        next_epoch(spec, state)
    _finalize_now(spec, state)
    # later indices get EARLIER eligibility epochs: sorting must win
    for k in range(mock_count):
        _queue_validator(spec, state, k, mock_count - k)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    activated = [k for k in range(mock_count)
                 if state.validators[k].activation_epoch
                 != spec.FAR_FUTURE_EPOCH]
    if churn is None:
        assert len(activated) == mock_count
    else:
        assert len(activated) == min(churn, mock_count)
        # the k with the LARGEST eligibility epochs (smallest k) are
        # the ones cut when the queue exceeds churn
        expected = sorted(
            range(mock_count),
            key=lambda k: (mock_count - k, k))[:churn]
        assert sorted(activated) == sorted(expected)


@with_all_phases
@spec_state_test
def test_activation_queue_efficiency(spec, state):
    """Two epochs of queue draining activate two churn batches
    pre-electra."""
    if spec.is_post("electra"):
        # unlimited activations: everything drains in one pass
        return
    from ...test_infra.blocks import next_epoch
    churn = int(spec.get_validator_churn_limit(state))
    mock_count = min(churn * 2, len(state.validators) - 1)
    for _ in range(3):
        next_epoch(spec, state)
    _finalize_now(spec, state)
    for k in range(mock_count):
        _queue_validator(spec, state, k, 1)
    spec.process_registry_updates(state)
    first_batch = [k for k in range(mock_count)
                   if state.validators[k].activation_epoch
                   != spec.FAR_FUTURE_EPOCH]
    assert len(first_batch) == min(churn, mock_count)
    # churn is per-invocation: the SECOND yielded pass drains the rest
    # (no epoch advance in between — next_epoch would run a full
    # process_epoch and activate the batch outside the vector)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    second_batch = [k for k in range(mock_count)
                    if state.validators[k].activation_epoch
                    != spec.FAR_FUTURE_EPOCH]
    assert len(second_batch) == min(churn * 2, mock_count)


@with_all_phases
@spec_state_test
def test_ejection_past_churn_limit(spec, state):
    """Ejections are NOT churn-limited: every low-balance validator
    exits, with exit epochs spread by the churn."""
    churn = int(spec.get_validator_churn_limit(state)) \
        if not spec.is_post("electra") else 2
    eject_count = min(churn + 2, len(state.validators) // 2)
    for k in range(eject_count):
        state.validators[k].effective_balance = uint64(
            int(spec.config.EJECTION_BALANCE))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert all(
        state.validators[k].exit_epoch != spec.FAR_FUTURE_EPOCH
        for k in range(eject_count))


@with_all_phases
@spec_state_test
def test_invalid_large_withdrawable_epoch(spec, state):
    """An exit whose withdrawable epoch would overflow uint64 makes the
    whole epoch transition fail (reference
    test_invalid_large_withdrawable_epoch)."""
    if spec.is_post("electra"):
        # electra draws exit epochs from the balance-churn accumulator,
        # not the registry max (beacon-chain.md:558-586)
        state.earliest_exit_epoch = spec.FAR_FUTURE_EPOCH - uint64(1)
    else:
        state.validators[0].exit_epoch = (
            spec.FAR_FUTURE_EPOCH - uint64(1))
    state.validators[1].effective_balance = uint64(
        int(spec.config.EJECTION_BALANCE))
    yield "pre", state.copy()
    try:
        slot = uint64(int(state.slot) + int(spec.SLOTS_PER_EPOCH)
                      - int(state.slot) % int(spec.SLOTS_PER_EPOCH))
        spec.process_slots(state, slot)
    except (ValueError, OverflowError):
        yield "post", None
        return
    raise AssertionError("uint64 overflow unexpectedly tolerated")


def _queue_validators(spec, state, count, eligibility_epoch=1):
    """Mark `count` existing validators as queued (eligible, not yet
    activated)."""
    out = []
    for i in range(count):
        v = state.validators[i]
        v.activation_epoch = spec.FAR_FUTURE_EPOCH
        v.activation_eligibility_epoch = uint64(eligibility_epoch)
        out.append(i)
    return out


def _finalize(spec, state, epochs_back=1):
    state.finalized_checkpoint.epoch = uint64(
        max(int(spec.get_current_epoch(state)) - epochs_back, 0))


@with_all_phases
@spec_state_test
def test_activation_queue_activation_and_ejection_1(spec, state):
    """One activation and one ejection in the same pass."""
    from ...test_infra.blocks import next_epoch
    next_epoch(spec, state)
    next_epoch(spec, state)
    queued = _queue_validators(spec, state, 1)
    _finalize(spec, state)
    eject = len(state.validators) - 1
    state.validators[eject].effective_balance = uint64(
        spec.config.EJECTION_BALANCE)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[queued[0]].activation_epoch != \
        spec.FAR_FUTURE_EPOCH
    assert state.validators[eject].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_activation_and_ejection_churn_limit(spec,
                                                              state):
    from ...test_infra.blocks import next_epoch
    next_epoch(spec, state)
    next_epoch(spec, state)
    churn = int(spec.get_validator_churn_limit(state))
    take = min(churn, len(state.validators) // 2)
    queued = _queue_validators(spec, state, take)
    _finalize(spec, state)
    for off in range(take):
        eject = len(state.validators) - 1 - off
        state.validators[eject].effective_balance = uint64(
            spec.config.EJECTION_BALANCE)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    # electra removed the activation churn gate; pre-electra all fit
    assert all(state.validators[i].activation_epoch !=
               spec.FAR_FUTURE_EPOCH for i in queued)


@with_all_phases
@spec_state_test
def test_activation_queue_exceed_churn_limit(spec, state):
    """More eligible validators than the churn limit: pre-electra only
    churn-many activate; electra (EIP-7251) activates all."""
    from ...test_infra.blocks import next_epoch
    next_epoch(spec, state)
    next_epoch(spec, state)
    churn = int(spec.get_validator_churn_limit(state))
    take = min(churn + 2, len(state.validators))
    queued = _queue_validators(spec, state, take)
    _finalize(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    activated = sum(
        1 for i in queued
        if state.validators[i].activation_epoch !=
        spec.FAR_FUTURE_EPOCH)
    if spec.is_post("electra"):
        assert activated == take
    else:
        assert activated == min(churn, take)


@with_all_phases
@spec_state_test
def test_ejection_exit_epochs_sequential_past_churn(spec, state):
    """Ejections beyond the exit churn spread across exit epochs."""
    churn = int(spec.get_validator_churn_limit(state)) \
        if not spec.is_post("electra") else 2
    take = min(churn * 2, len(state.validators) // 2)
    for i in range(take):
        state.validators[i].effective_balance = uint64(
            spec.config.EJECTION_BALANCE)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    epochs = [int(state.validators[i].exit_epoch) for i in range(take)]
    assert all(e != int(spec.FAR_FUTURE_EPOCH) for e in epochs)
    if not spec.is_post("electra") and take > churn:
        assert len(set(epochs)) >= 2


@with_all_phases
@spec_state_test
def test_eligibility_requires_max_effective_balance(spec, state):
    """Below-threshold validators never enter the activation queue."""
    from ...test_infra.genesis import build_mock_validator
    fresh = build_mock_validator(
        spec, len(state.validators),
        uint64(int(spec.MAX_EFFECTIVE_BALANCE) // 2))
    fresh.activation_eligibility_epoch = spec.FAR_FUTURE_EPOCH
    fresh.activation_epoch = spec.FAR_FUTURE_EPOCH
    state.validators.append(fresh)
    state.balances.append(uint64(int(spec.MAX_EFFECTIVE_BALANCE) // 2))
    if spec.is_post("altair"):
        state.previous_epoch_participation.append(0)
        state.current_epoch_participation.append(0)
        state.inactivity_scores.append(0)
    index = len(state.validators) - 1
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch == \
        spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_already_exited_not_ejected_again(spec, state):
    """A low-balance validator that already initiated exit keeps its
    exit epoch."""
    index = 4
    spec.initiate_validator_exit(state, uint64(index))
    before = int(state.validators[index].exit_epoch)
    state.validators[index].effective_balance = uint64(
        spec.config.EJECTION_BALANCE)
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert int(state.validators[index].exit_epoch) == before


# ---------------------------------------------------------------------------
# eligibility balance thresholds (electra: eligibility keys off
# MIN_ACTIVATION_BALANCE; credentials don't change the threshold)
# ---------------------------------------------------------------------------

def _append_fresh_validator(spec, state, balance, creds_prefix=None):
    fresh = build_mock_validator(
        spec, len(state.validators), balance)
    if creds_prefix is not None:
        fresh.withdrawal_credentials = bytes([creds_prefix]) \
            + bytes(fresh.withdrawal_credentials)[1:]
    state.validators.append(fresh)
    state.balances.append(uint64(balance))
    if spec.is_post("altair"):
        state.previous_epoch_participation.append(0)
        state.current_epoch_participation.append(0)
        state.inactivity_scores.append(0)
    return len(state.validators) - 1


@with_all_phases_from("electra")
@spec_state_test
def test_activation_queue_eligibility__less_than_min_activation_balance(
        spec, state):
    index = _append_fresh_validator(
        spec, state,
        int(spec.MIN_ACTIVATION_BALANCE)
        - int(spec.EFFECTIVE_BALANCE_INCREMENT))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch == \
        spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_activation_queue_eligibility__min_activation_balance(spec,
                                                              state):
    index = _append_fresh_validator(
        spec, state, int(spec.MIN_ACTIVATION_BALANCE))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch != \
        spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_activation_queue_eligibility__min_activation_balance_eth1_creds(
        spec, state):
    index = _append_fresh_validator(
        spec, state, int(spec.MIN_ACTIVATION_BALANCE),
        creds_prefix=int.from_bytes(
            bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX), "big"))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch != \
        spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_activation_queue_eligibility__min_activation_balance_compounding_creds(
        spec, state):
    index = _append_fresh_validator(
        spec, state, int(spec.MIN_ACTIVATION_BALANCE),
        creds_prefix=int.from_bytes(
            bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX), "big"))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch != \
        spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_activation_queue_eligibility__greater_than_min_activation_balance(
        spec, state):
    index = _append_fresh_validator(
        spec, state,
        int(spec.MIN_ACTIVATION_BALANCE)
        + int(spec.EFFECTIVE_BALANCE_INCREMENT))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch != \
        spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_activation_queue_to_activated_if_finalized(spec, state):
    """Eligible + finalized ancestor => activated at the churned
    epoch."""
    index = 4
    v = state.validators[index]
    v.activation_epoch = spec.FAR_FUTURE_EPOCH
    v.activation_eligibility_epoch = uint64(0)
    state.finalized_checkpoint.epoch = uint64(
        int(spec.get_current_epoch(state)))
    expected_activation = spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    # activated at exactly the churned activation-exit epoch
    assert int(state.validators[index].activation_epoch) == \
        int(expected_activation)
    assert spec.is_active_validator(state.validators[index],
                                    expected_activation)


@with_all_phases
@spec_state_test
def test_ejection_and_activation_interleaved(spec, state):
    """One ejection and one activation processed in the same pass."""
    eject = 2
    activate = 5
    state.validators[eject].effective_balance = uint64(
        spec.config.EJECTION_BALANCE)
    v = state.validators[activate]
    v.activation_epoch = spec.FAR_FUTURE_EPOCH
    v.activation_eligibility_epoch = uint64(0)
    state.finalized_checkpoint.epoch = uint64(
        int(spec.get_current_epoch(state)))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[eject].exit_epoch != spec.FAR_FUTURE_EPOCH
    assert state.validators[activate].activation_epoch != \
        spec.FAR_FUTURE_EPOCH
