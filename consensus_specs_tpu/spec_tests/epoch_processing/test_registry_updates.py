"""process_registry_updates epoch tests (eligibility, ejection,
activation queue)."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases
from ...test_infra.epoch_processing import run_epoch_processing_with
from ...test_infra.genesis import build_mock_validator


@with_all_phases
@spec_state_test
def test_new_validator_becomes_eligible(spec, state):
    fresh = build_mock_validator(
        spec, len(state.validators), spec.MAX_EFFECTIVE_BALANCE)
    state.validators.append(fresh)
    state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    if spec.is_post("altair"):
        state.previous_epoch_participation.append(0)
        state.current_epoch_participation.append(0)
        state.inactivity_scores.append(0)
    index = len(state.validators) - 1
    assert state.validators[index].activation_eligibility_epoch == \
        spec.FAR_FUTURE_EPOCH
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_eligibility_epoch != \
        spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_low_balance_validator_ejected(spec, state):
    index = 2
    state.validators[index].effective_balance = uint64(
        spec.config.EJECTION_BALANCE)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases
@spec_state_test
def test_eligible_validator_gets_activated(spec, state):
    index = 3
    v = state.validators[index]
    v.activation_epoch = spec.FAR_FUTURE_EPOCH
    v.activation_eligibility_epoch = uint64(0)
    state.finalized_checkpoint.epoch = uint64(
        int(spec.get_current_epoch(state)))
    yield from run_epoch_processing_with(
        spec, state, "process_registry_updates")
    assert state.validators[index].activation_epoch != \
        spec.FAR_FUTURE_EPOCH
