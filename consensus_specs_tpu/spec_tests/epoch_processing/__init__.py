"""Epoch-processing spec tests (pre + post vectors per sub-pass)."""

EPOCH_PROCESSING_HANDLERS = {
    "justification_and_finalization":
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_justification_and_finalization",
    "effective_balance_updates":
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_effective_balance_updates",
    "slashings":
        "consensus_specs_tpu.spec_tests.epoch_processing.test_slashings",
    "registry_updates":
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_registry_updates",
    "resets":
        "consensus_specs_tpu.spec_tests.epoch_processing.test_resets",
    "participation_updates":
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_participation_updates",
    "pending_queues": [
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_pending_queues",
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_apply_pending_deposit",
    ],
    "rewards_and_penalties":
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_rewards_and_penalties",
    "sync_committee_updates":
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_sync_committee_updates",
    "inactivity_updates":
        "consensus_specs_tpu.spec_tests.epoch_processing."
        "test_inactivity_updates",
}
