"""Light-client sync-protocol unit battery (reference
test/altair/unittests/light_client/test_sync_protocol.py, 4 defs):
process_light_client_update store-state assertions around timeouts,
period boundaries, and finality advances."""
import pytest

from ...ssz import hash_tree_root, uint64
from ...test_infra.context import (
    spec_state_test, no_vectors, with_all_phases_from, with_presets,
    with_pytest_fork_subset, always_bls, _genesis_state,
    default_balances, default_activation_threshold)
from ...test_infra.attestations import (
    next_epoch_with_attestations, state_transition_with_full_block)
from ...test_infra.blocks import transition_to
from ...test_infra.light_client_sync import build_sync_aggregate
from ...ssz.proofs import compute_merkle_proof

LC_FORKS = ["altair", "capella"]


def _lc_spec_and_state(spec):
    """LC protocol functions are fork-epoch-gated; pin every active
    fork's epoch to 0 (the with_config_overrides LC pattern of
    test_sync.py) and build a genesis state under that config."""
    from ...specs import get_spec
    overrides = {}
    for name in ["ALTAIR", "BELLATRIX", "CAPELLA", "DENEB", "ELECTRA",
                 "FULU"]:
        if spec.is_post(name.lower()):
            overrides[f"{name}_FORK_EPOCH"] = 0
    spec = get_spec(spec.fork, spec.preset_name,
                    spec.config.replace(**overrides))
    state = _genesis_state(spec, default_balances,
                           default_activation_threshold, "lc-units")
    return spec, state


def _setup_test(spec, state):
    trusted_block = spec.SignedBeaconBlock()
    trusted_block.message.state_root = hash_tree_root(state)
    trusted_block_root = hash_tree_root(trusted_block.message)
    bootstrap = spec.create_light_client_bootstrap(state, trusted_block)
    store = spec.initialize_light_client_store(trusted_block_root,
                                               bootstrap)
    store.next_sync_committee = state.next_sync_committee
    return trusted_block, store


def _create_update(spec, attested_state, attested_block, finalized_block,
                   with_next, with_finality, participation_rate):
    """Update with independently togglable next-committee and finality
    sections (reference helpers/light_client.py::create_update)."""
    types = spec._lc()
    update = types["LightClientUpdate"]()
    update.attested_header = spec.block_to_light_client_header(
        attested_block)
    if with_next:
        update.next_sync_committee = attested_state.next_sync_committee
        update.next_sync_committee_branch = compute_merkle_proof(
            attested_state, spec.next_sync_committee_gindex_at_slot(
                attested_state.slot))
    if with_finality:
        update.finalized_header = spec.block_to_light_client_header(
            finalized_block)
        update.finality_branch = compute_merkle_proof(
            attested_state, spec.finalized_root_gindex_at_slot(
                attested_state.slot))
    signature_slot = uint64(int(attested_block.message.slot) + 1)
    update.sync_aggregate = build_sync_aggregate(
        spec, attested_state, signature_slot,
        hash_tree_root(attested_block.message),
        participation=participation_rate)
    update.signature_slot = signature_slot
    return update


@with_all_phases_from("altair")
@with_pytest_fork_subset(LC_FORKS)
@spec_state_test
@no_vectors
@always_bls
def test_process_light_client_update_not_timeout(spec, state):
    spec, state = _lc_spec_and_state(spec)
    genesis_block, store = _setup_test(spec, state)
    attested_block = state_transition_with_full_block(spec, state,
                                                      False, False)
    signature_slot = uint64(int(state.slot) + 1)
    assert int(state.finalized_checkpoint.epoch) == 0
    update = _create_update(spec, state, attested_block, genesis_block,
                            with_next=False, with_finality=False,
                            participation_rate=1.0)
    pre_finalized = store.finalized_header.copy()
    spec.process_light_client_update(store, update, signature_slot,
                                     state.genesis_validators_root)
    assert store.finalized_header == pre_finalized
    assert store.best_valid_update == update
    assert store.optimistic_header == update.attested_header
    assert int(store.current_max_active_participants) > 0


@pytest.mark.slow  # ~6 s UPDATE_TIMEOUT walk under always_bls; not_timeout + timeout keep the quick signal on both period branches
@with_all_phases_from("altair")
@with_pytest_fork_subset(LC_FORKS)
@with_presets(["minimal"], reason="too slow")
@spec_state_test
@no_vectors
@always_bls
def test_process_light_client_update_at_period_boundary(spec, state):
    spec, state = _lc_spec_and_state(spec)
    genesis_block, store = _setup_test(spec, state)
    # final slot of the store's period
    transition_to(spec, state,
                  uint64(int(state.slot) + int(spec.UPDATE_TIMEOUT) - 2))
    store_period = spec.compute_sync_committee_period_at_slot(
        store.optimistic_header.beacon.slot)
    update_period = spec.compute_sync_committee_period_at_slot(
        state.slot)
    assert store_period == update_period
    attested_block = state_transition_with_full_block(spec, state,
                                                      False, False)
    signature_slot = uint64(int(state.slot) + 1)
    update = _create_update(spec, state, attested_block, genesis_block,
                            with_next=False, with_finality=False,
                            participation_rate=1.0)
    pre_finalized = store.finalized_header.copy()
    spec.process_light_client_update(store, update, signature_slot,
                                     state.genesis_validators_root)
    assert store.finalized_header == pre_finalized
    assert store.best_valid_update == update
    assert store.optimistic_header == update.attested_header
    assert int(store.current_max_active_participants) > 0


@with_all_phases_from("altair")
@with_pytest_fork_subset(LC_FORKS)
@with_presets(["minimal"], reason="too slow")
@spec_state_test
@no_vectors
@always_bls
def test_process_light_client_update_timeout(spec, state):
    spec, state = _lc_spec_and_state(spec)
    genesis_block, store = _setup_test(spec, state)
    # into the next sync-committee period
    transition_to(spec, state,
                  uint64(int(state.slot) + int(spec.UPDATE_TIMEOUT)))
    store_period = spec.compute_sync_committee_period_at_slot(
        store.optimistic_header.beacon.slot)
    update_period = spec.compute_sync_committee_period_at_slot(
        state.slot)
    assert store_period + 1 == update_period
    attested_block = state_transition_with_full_block(spec, state,
                                                      False, False)
    signature_slot = uint64(int(state.slot) + 1)
    update = _create_update(spec, state, attested_block, genesis_block,
                            with_next=True, with_finality=False,
                            participation_rate=1.0)
    pre_finalized = store.finalized_header.copy()
    spec.process_light_client_update(store, update, signature_slot,
                                     state.genesis_validators_root)
    assert store.finalized_header == pre_finalized
    assert store.best_valid_update == update
    assert store.optimistic_header == update.attested_header
    assert int(store.current_max_active_participants) > 0


@pytest.mark.slow  # three signed attested epochs under always_bls (~3 min)
@with_all_phases_from("altair")
@with_pytest_fork_subset(LC_FORKS)
@with_presets(["minimal"], reason="too slow")
@spec_state_test
@no_vectors
@always_bls
def test_process_light_client_update_finality_updated(spec, state):
    spec, state = _lc_spec_and_state(spec)
    _genesis_block, store = _setup_test(spec, state)
    # build three attested epochs so finality advances to epoch 3
    blocks = []
    transition_to(spec, state,
                  uint64(int(state.slot) + 2 * int(spec.SLOTS_PER_EPOCH)))
    for _ in range(3):
        new_blocks, state = next_epoch_with_attestations(
            spec, state, True, True)
        blocks += new_blocks
    assert int(state.finalized_checkpoint.epoch) == 3
    store_period = spec.compute_sync_committee_period_at_slot(
        store.optimistic_header.beacon.slot)
    update_period = spec.compute_sync_committee_period_at_slot(
        state.slot)
    assert store_period == update_period

    attested_block = blocks[-1]
    signature_slot = uint64(int(state.slot) + 1)
    finalized_block = blocks[int(spec.SLOTS_PER_EPOCH) - 1]
    assert int(finalized_block.message.slot) == int(
        spec.compute_start_slot_at_epoch(state.finalized_checkpoint.epoch))
    assert hash_tree_root(finalized_block.message) \
        == state.finalized_checkpoint.root

    update = _create_update(spec, state, attested_block, finalized_block,
                            with_next=False, with_finality=True,
                            participation_rate=1.0)
    spec.process_light_client_update(store, update, signature_slot,
                                     state.genesis_validators_root)
    assert store.finalized_header == update.finalized_header
    assert store.best_valid_update is None
    assert store.optimistic_header == update.attested_header
    assert int(store.current_max_active_participants) > 0
