"""Fulu custody unit battery (reference
test/fulu/unittests/test_custody.py, 5 defs)."""
from ...test_infra.context import (
    spec_test, no_vectors, with_all_phases_from)


def _run_get_custody_columns(spec, peer_count, custody_group_count):
    assignments = [spec.get_custody_groups(node_id, custody_group_count)
                   for node_id in range(peer_count)]
    columns_per_group = int(spec.config.NUMBER_OF_COLUMNS) \
        // int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    for assignment in assignments:
        columns = []
        for group in assignment:
            group_columns = spec.compute_columns_for_custody_group(group)
            assert len(group_columns) == columns_per_group
            columns.extend(group_columns)
        assert len(columns) == int(custody_group_count) \
            * columns_per_group
        assert len(columns) == len(set(columns))


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_custody_columns_peers_within_number_of_columns(spec):
    peer_count = 10
    assert int(spec.config.NUMBER_OF_COLUMNS) > peer_count
    _run_get_custody_columns(spec, peer_count,
                             spec.config.CUSTODY_REQUIREMENT)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_custody_columns_peers_more_than_number_of_columns(spec):
    peer_count = 200
    assert int(spec.config.NUMBER_OF_COLUMNS) < peer_count
    _run_get_custody_columns(spec, peer_count,
                             spec.config.CUSTODY_REQUIREMENT)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_custody_columns_maximum_groups(spec):
    _run_get_custody_columns(spec, 10,
                             spec.config.NUMBER_OF_CUSTODY_GROUPS)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_custody_columns_custody_size_more_than_number_of_groups(
        spec):
    try:
        spec.get_custody_groups(
            1, int(spec.config.NUMBER_OF_CUSTODY_GROUPS) + 1)
        raise RuntimeError("oversized custody request accepted")
    except AssertionError:
        pass


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_compute_columns_for_custody_group_out_of_bound_custody_group(
        spec):
    try:
        spec.compute_columns_for_custody_group(
            int(spec.config.NUMBER_OF_CUSTODY_GROUPS))
        raise RuntimeError("out-of-bound custody group accepted")
    except AssertionError:
        pass
