"""Deneb KZG polynomial-commitment unit battery (reference
test/deneb/unittests/polynomial_commitments/
test_polynomial_commitments.py, 17 defs): proof round trips at the
_impl and bytes tiers, barycentric evaluation in/out of domain, field
deserialization bounds, G1 input validation.

Polynomials here are SPARSE (few nonzero coefficients) so the
pure-Python oracle stays fast; the algebraic identities under test are
degree-independent."""
import random

from ...crypto.kzg import BLS_MODULUS, KZG_ENDIANNESS
from ...test_infra.blob import get_sample_blob
from ...test_infra.context import (
    spec_test, no_vectors, with_all_phases_from)
from ...utils import bls

P1_NOT_IN_G1 = bytes.fromhex(
    "8123456789abcdef0123456789abcdef0123456789abcdef"
    "0123456789abcdef0123456789abcdef0123456789abcdef")
P1_NOT_ON_CURVE = bytes.fromhex(
    "8123456789abcdef0123456789abcdef0123456789abcdef"
    "0123456789abcdef0123456789abcdef0123456789abcde0")


def _bls_add_one(x):
    """Add the generator to a compressed G1 point: a definitely
    incorrect proof that still deserializes."""
    return bls.G1_to_bytes48(bls.add(bls.bytes48_to_G1(x), bls.G1()))


def _sparse_poly_in_both_forms(spec, rng, nonzero=8):
    """(coeffs, evals) for a sparse random polynomial; evals computed
    term-by-term so building the evaluation form costs O(n * nonzero)
    instead of O(n^2)."""
    n = int(spec.FIELD_ELEMENTS_PER_BLOB)
    roots_brp = spec.bit_reversal_permutation(
        spec.compute_roots_of_unity(n))
    coeffs = [0] * n
    for _ in range(nonzero):
        coeffs[rng.randrange(n)] = rng.randint(0, BLS_MODULUS - 1)
    terms = [(j, c) for j, c in enumerate(coeffs) if c]
    evals = [sum(c * pow(int(z), j, BLS_MODULUS) for j, c in terms)
             % BLS_MODULUS for z in roots_brp]
    return coeffs, evals


def _eval_poly_in_coeff_form(coeffs, x):
    total = 0
    for a in reversed(coeffs):
        total = (total * x + a) % BLS_MODULUS
    return total


# --- proof round trips ----------------------------------------------------

@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_verify_kzg_proof(spec):
    x = spec.bls_field_to_bytes(3)
    blob = get_sample_blob(spec)
    commitment = spec.blob_to_kzg_commitment(blob)
    proof, y = spec.compute_kzg_proof(blob, x)
    assert spec.verify_kzg_proof(commitment, x, y, proof)


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_verify_kzg_proof_incorrect_proof(spec):
    x = spec.bls_field_to_bytes(3465)
    blob = get_sample_blob(spec)
    commitment = spec.blob_to_kzg_commitment(blob)
    proof, y = spec.compute_kzg_proof(blob, x)
    proof = _bls_add_one(proof)
    assert not spec.verify_kzg_proof(commitment, x, y, proof)


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_verify_kzg_proof_impl(spec):
    x = BLS_MODULUS - 1
    blob = get_sample_blob(spec)
    commitment = spec.blob_to_kzg_commitment(blob)
    polynomial = spec.blob_to_polynomial(blob)
    proof, y = spec.compute_kzg_proof_impl(polynomial, x)
    assert spec.verify_kzg_proof_impl(commitment, x, y, proof)


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_verify_kzg_proof_impl_incorrect_proof(spec):
    x = 324561
    blob = get_sample_blob(spec)
    commitment = spec.blob_to_kzg_commitment(blob)
    polynomial = spec.blob_to_polynomial(blob)
    proof, y = spec.compute_kzg_proof_impl(polynomial, x)
    proof = _bls_add_one(proof)
    assert not spec.verify_kzg_proof_impl(commitment, x, y, proof)


# --- barycentric evaluation -----------------------------------------------

@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_barycentric_outside_domain(spec):
    rng = random.Random(5566)
    poly_coeff, poly_eval = _sparse_poly_in_both_forms(spec, rng)
    roots_brp = spec.bit_reversal_permutation(
        spec.compute_roots_of_unity(spec.FIELD_ELEMENTS_PER_BLOB))
    assert len(poly_coeff) == len(poly_eval) == len(roots_brp)
    root_set = {int(z) for z in roots_brp}
    for _ in range(12):
        z = rng.randint(0, BLS_MODULUS - 1)
        while z in root_set:
            z = rng.randint(0, BLS_MODULUS - 1)
        p_z_coeff = _eval_poly_in_coeff_form(poly_coeff, z)
        p_z_eval = spec.evaluate_polynomial_in_evaluation_form(
            poly_eval, z)
        assert int(p_z_eval) == p_z_coeff


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_barycentric_within_domain(spec):
    rng = random.Random(5566)
    poly_coeff, poly_eval = _sparse_poly_in_both_forms(spec, rng)
    roots_brp = spec.bit_reversal_permutation(
        spec.compute_roots_of_unity(spec.FIELD_ELEMENTS_PER_BLOB))
    n = len(poly_coeff)
    for _ in range(12):
        i = rng.randint(0, n - 1)
        z = int(roots_brp[i])
        p_z_coeff = _eval_poly_in_coeff_form(poly_coeff, z)
        p_z_eval = spec.evaluate_polynomial_in_evaluation_form(
            poly_eval, z)
        assert int(p_z_eval) == p_z_coeff == poly_eval[i]


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_compute_kzg_proof_within_domain(spec):
    rng = random.Random(5566)
    blob = get_sample_blob(spec)
    commitment = spec.blob_to_kzg_commitment(blob)
    polynomial = spec.blob_to_polynomial(blob)
    roots_brp = spec.bit_reversal_permutation(
        spec.compute_roots_of_unity(spec.FIELD_ELEMENTS_PER_BLOB))
    for _ in range(3):
        z = int(rng.choice(roots_brp))
        proof, y = spec.compute_kzg_proof_impl(polynomial, z)
        assert spec.verify_kzg_proof_impl(commitment, z, y, proof)


# --- blob proofs ----------------------------------------------------------

@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_verify_blob_kzg_proof(spec):
    blob = get_sample_blob(spec)
    commitment = spec.blob_to_kzg_commitment(blob)
    proof = spec.compute_blob_kzg_proof(blob, commitment)
    assert spec.verify_blob_kzg_proof(blob, commitment, proof)


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_verify_blob_kzg_proof_incorrect_proof(spec):
    blob = get_sample_blob(spec)
    commitment = spec.blob_to_kzg_commitment(blob)
    proof = spec.compute_blob_kzg_proof(blob, commitment)
    proof = _bls_add_one(proof)
    assert not spec.verify_blob_kzg_proof(blob, commitment, proof)


# --- field deserialization bounds -----------------------------------------

@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_bytes_to_bls_field_zero(spec):
    assert int(spec.bytes_to_bls_field(b"\x00" * 32)) == 0


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_bytes_to_bls_field_modulus_minus_one(spec):
    b = (BLS_MODULUS - 1).to_bytes(32, KZG_ENDIANNESS)
    assert int(spec.bytes_to_bls_field(b)) == BLS_MODULUS - 1


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_bytes_to_bls_field_modulus(spec):
    b = BLS_MODULUS.to_bytes(32, KZG_ENDIANNESS)
    try:
        spec.bytes_to_bls_field(b)
        raise RuntimeError("modulus accepted as field element")
    except (AssertionError, ValueError):
        pass


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_bytes_to_bls_field_max(spec):
    b = b"\xff" * 32
    try:
        spec.bytes_to_bls_field(b)
        raise RuntimeError("2**256-1 accepted as field element")
    except (AssertionError, ValueError):
        pass


# --- G1 input validation --------------------------------------------------

@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_validate_kzg_g1_generator(spec):
    spec.validate_kzg_g1(bls.G1_to_bytes48(bls.G1()))


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_validate_kzg_g1_neutral_element(spec):
    spec.validate_kzg_g1(b"\xc0" + b"\x00" * 47)


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_validate_kzg_g1_not_in_g1(spec):
    try:
        spec.validate_kzg_g1(P1_NOT_IN_G1)
        raise RuntimeError("point outside G1 accepted")
    except (AssertionError, ValueError):
        pass


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_validate_kzg_g1_not_on_curve(spec):
    try:
        spec.validate_kzg_g1(P1_NOT_ON_CURVE)
        raise RuntimeError("point off the curve accepted")
    except (AssertionError, ValueError):
        pass
