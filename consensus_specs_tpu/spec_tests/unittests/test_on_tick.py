"""on_tick unit battery (reference
test/phase0/unittests/fork_choice/test_on_tick.py)."""
from ...ssz import hash_tree_root, uint64
from ...test_infra.context import (
    spec_state_test, no_vectors, with_all_phases, never_bls)
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, next_epoch,
    state_transition_and_sign_block, transition_to)
from ...test_infra.fork_choice import get_genesis_forkchoice_store


def _run_on_tick(spec, store, time, new_justified_checkpoint=False):
    previous = store.justified_checkpoint.copy()
    spec.on_tick(store, int(time))
    assert int(store.time) == int(time)
    if new_justified_checkpoint:
        assert int(store.justified_checkpoint.epoch) > int(previous.epoch)
        assert store.justified_checkpoint.root != previous.root
    else:
        assert store.justified_checkpoint == previous


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_basic(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    _run_on_tick(spec, store, int(store.time) + 1)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_update_justified_single_not_on_store_finalized_chain(
        spec, state):
    """An unrealized-justification candidate on a branch CONFLICTING
    with the store's finalized checkpoint must not be adopted at the
    epoch tick."""
    store = get_genesis_forkchoice_store(spec, state)
    init_state = state.copy()

    # branch 1: a block at epoch 1, then finalize the store on it
    next_epoch(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.graffiti = b"\x11" * 32
    state_transition_and_sign_block(spec, state, block)
    store.blocks[hash_tree_root(block)] = block.copy()
    store.block_states[hash_tree_root(block)] = state.copy()
    store.finalized_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(block.slot),
        root=hash_tree_root(block))

    # branch 2: a conflicting epoch-1 block whose descendant claims
    # justification of it at the epoch-2 boundary
    state = init_state.copy()
    next_epoch(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.graffiti = b"\x22" * 32
    state_transition_and_sign_block(spec, state, block)
    store.blocks[hash_tree_root(block)] = block.copy()
    store.block_states[hash_tree_root(block)] = state.copy()
    parent_block = block.copy()
    transition_to(
        spec, state,
        uint64(int(state.slot) + int(spec.SLOTS_PER_EPOCH)
               - int(state.slot) % int(spec.SLOTS_PER_EPOCH) - 1))
    block = build_empty_block_for_next_slot(spec, state)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=spec.compute_epoch_at_slot(parent_block.slot),
        root=hash_tree_root(parent_block))
    state_transition_and_sign_block(spec, state, block)
    store.blocks[hash_tree_root(block)] = block.copy()
    store.block_states[hash_tree_root(block)] = state.copy()

    _run_on_tick(
        spec, store,
        int(store.genesis_time)
        + int(state.slot) * int(spec.config.SECONDS_PER_SLOT))


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_tick_through_epoch_boundary_adopts_unrealized(spec, state):
    """Crossing an epoch boundary promotes the store's unrealized
    checkpoints (fork-choice.md on_tick_per_slot)."""
    store = get_genesis_forkchoice_store(spec, state)
    # hand the store an unrealized justification on the anchor chain
    anchor_root = store.justified_checkpoint.root
    store.unrealized_justified_checkpoint = spec.Checkpoint(
        epoch=uint64(int(store.justified_checkpoint.epoch) + 1),
        root=anchor_root)
    store.unrealized_finalized_checkpoint = \
        store.finalized_checkpoint.copy()
    target = (int(store.genesis_time)
              + int(spec.SLOTS_PER_EPOCH)
              * int(spec.config.SECONDS_PER_SLOT))
    spec.on_tick(store, target)
    assert int(store.time) == target
    assert store.justified_checkpoint \
        == store.unrealized_justified_checkpoint
