"""Math helper units (reference
test/phase0/unittests/math/test_integer_squareroot.py)."""
import random
from math import isqrt

from ...ssz import uint64
from ...test_infra.context import spec_test, no_vectors, with_all_phases


@with_all_phases
@spec_test
@no_vectors
def test_integer_squareroot(spec):
    for n in (0, 100, 2**64 - 2, 2**64 - 1):
        assert int(spec.integer_squareroot(uint64(n))) == isqrt(n)
    rng = random.Random(5566)
    for _ in range(10):
        n = rng.randint(0, 2**64 - 1)
        assert int(spec.integer_squareroot(uint64(n))) == isqrt(n)
    # out-of-range input is rejected at the type boundary
    try:
        spec.integer_squareroot(uint64(2**64))
        raise AssertionError("uint64 overflow accepted")
    except ValueError:
        pass
