"""Altair sync-committee validator-duty unit battery (reference
test/altair/unittests/validator/test_validator.py, 9 defs)."""
import random
from collections import defaultdict

from ...ssz import Bytes32, uint64
from ...test_infra.context import (
    spec_state_test, no_vectors, with_all_phases_from, with_presets,
    always_bls)
from ...test_infra.blocks import build_empty_block, transition_to
from ...test_infra.keys import privkeys, pubkeys, privkey_for_pubkey
from ...utils import bls

rng = random.Random(1337)


def _ensure_assignments_in_sync_committee(spec, state, epoch,
                                          sync_committee, active_pubkeys):
    assert len(sync_committee.pubkeys) >= 3
    some_pubkeys = rng.sample(list(sync_committee.pubkeys), 3)
    for pubkey in some_pubkeys:
        validator_index = active_pubkeys.index(pubkey)
        assert spec.is_assigned_to_sync_committee(state, epoch,
                                                  validator_index)


@with_all_phases_from("altair")
@spec_state_test
@no_vectors
def test_is_assigned_to_sync_committee(spec, state):
    epoch = spec.get_current_epoch(state)
    validator_indices = spec.get_active_validator_indices(state, epoch)
    query_epoch = uint64(int(epoch) + 1)
    next_query_epoch = uint64(
        int(query_epoch) + int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD))
    active_pubkeys = [state.validators[i].pubkey
                      for i in validator_indices]
    _ensure_assignments_in_sync_committee(
        spec, state, query_epoch, state.current_sync_committee,
        active_pubkeys)
    _ensure_assignments_in_sync_committee(
        spec, state, next_query_epoch, state.next_sync_committee,
        active_pubkeys)
    committee_pubkeys = set(
        list(state.current_sync_committee.pubkeys)
        + list(state.next_sync_committee.pubkeys))
    disqualified = sorted(
        bytes(k) for k in active_pubkeys if k not in committee_pubkeys)
    if disqualified:
        for pubkey in rng.sample(disqualified, min(3, len(disqualified))):
            validator_index = [bytes(k) for k in active_pubkeys].index(
                pubkey)
            assert not (
                spec.is_assigned_to_sync_committee(
                    state, query_epoch, validator_index)
                or spec.is_assigned_to_sync_committee(
                    state, next_query_epoch, validator_index))


def _sync_committee_signature_for(spec, state, target_slot,
                                  target_block_root, subcommittee_index,
                                  index_in_subcommittee):
    subcommittee_size = int(spec.SYNC_COMMITTEE_SIZE) \
        // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    position = subcommittee_index * subcommittee_size \
        + index_in_subcommittee
    pubkey = state.current_sync_committee.pubkeys[position]
    privkey = privkey_for_pubkey(pubkey)
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE,
                             spec.compute_epoch_at_slot(target_slot))
    signing_root = spec.compute_signing_root(
        Bytes32(target_block_root), domain)
    return bls.Sign(privkey, signing_root)


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="too slow")
@spec_state_test
@no_vectors
@always_bls
def test_process_sync_committee_contributions(spec, state):
    transition_to(spec, state, uint64(int(state.slot) + 3))
    block = build_empty_block(spec, state)
    previous_slot = uint64(int(state.slot) - 1)
    target_block_root = spec.get_block_root_at_slot(state, previous_slot)
    subcommittee_size = int(spec.SYNC_COMMITTEE_SIZE) \
        // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    bits_type = type(block.body.sync_aggregate.sync_committee_bits)

    aggregation_index = 0
    contributions = []
    for i in range(int(spec.SYNC_COMMITTEE_SUBNET_COUNT)):
        aggregation_bits = [False] * subcommittee_size
        aggregation_bits[aggregation_index] = True
        contributions.append(spec.SyncCommitteeContribution(
            slot=block.slot,
            beacon_block_root=target_block_root,
            subcommittee_index=uint64(i),
            aggregation_bits=aggregation_bits,
            signature=_sync_committee_signature_for(
                spec, state, previous_slot, target_block_root, i,
                aggregation_index)))

    # empty aggregate before ...
    assert not any(block.body.sync_aggregate.sync_committee_bits)
    assert bytes(block.body.sync_aggregate.sync_committee_signature) \
        == bytes(spec.G2_POINT_AT_INFINITY)
    spec.process_sync_committee_contributions(block, contributions)
    # ... non-empty and VALID after
    assert any(block.body.sync_aggregate.sync_committee_bits)
    assert bytes(block.body.sync_aggregate.sync_committee_signature) \
        != bytes(spec.G2_POINT_AT_INFINITY)
    assert isinstance(block.body.sync_aggregate.sync_committee_bits,
                      bits_type)
    spec.process_block(state, block)


@with_all_phases_from("altair")
@spec_state_test
@no_vectors
@always_bls
def test_get_sync_committee_message(spec, state):
    validator_index = 0
    block_root = b"\x12" * 32
    message = spec.get_sync_committee_message(
        state=state, block_root=block_root,
        validator_index=validator_index,
        privkey=privkeys[validator_index])
    assert message.slot == state.slot
    assert bytes(message.beacon_block_root) == block_root
    assert message.validator_index == validator_index
    epoch = spec.get_current_epoch(state)
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE, epoch)
    signing_root = spec.compute_signing_root(Bytes32(block_root), domain)
    assert bytes(message.signature) == bytes(
        bls.Sign(privkeys[validator_index], signing_root))


def _subnet_for_sync_committee_index(spec, i):
    return i // (int(spec.SYNC_COMMITTEE_SIZE)
                 // int(spec.SYNC_COMMITTEE_SUBNET_COUNT))


def _expected_subnets_by_pubkey(members):
    expected = defaultdict(set)
    for subnet, pubkey in members:
        expected[bytes(pubkey)].add(subnet)
    return expected


def _check_subnets_against_committee(spec, state, committee):
    members = [(_subnet_for_sync_committee_index(spec, i), pubkey)
               for i, pubkey in enumerate(committee.pubkeys)]
    expected = _expected_subnets_by_pubkey(members)
    all_pubkeys = [bytes(v.pubkey) for v in state.validators]
    for _, pubkey in members:
        validator_index = all_pubkeys.index(bytes(pubkey))
        subnets = spec.compute_subnets_for_sync_committee(
            state, validator_index)
        assert {int(s) for s in subnets} \
            == {int(s) for s in expected[bytes(pubkey)]}


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="too slow")
@spec_state_test
@no_vectors
def test_compute_subnets_for_sync_committee(spec, state):
    # head of the next period: next slot stays in the SAME period
    transition_to(spec, state,
                  uint64(int(spec.SLOTS_PER_EPOCH)
                         * int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)))
    next_slot_epoch = spec.compute_epoch_at_slot(
        uint64(int(state.slot) + 1))
    assert spec.compute_sync_committee_period(
        spec.get_current_epoch(state)) \
        == spec.compute_sync_committee_period(next_slot_epoch)
    _check_subnets_against_committee(spec, state,
                                     state.current_sync_committee)


@with_all_phases_from("altair")
@with_presets(["minimal"], reason="too slow")
@spec_state_test
@no_vectors
def test_compute_subnets_for_sync_committee_slot_period_boundary(
        spec, state):
    # end of the period: next slot crosses into the NEXT period
    transition_to(spec, state,
                  uint64(int(spec.SLOTS_PER_EPOCH)
                         * int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
                         - 1))
    next_slot_epoch = spec.compute_epoch_at_slot(
        uint64(int(state.slot) + 1))
    assert spec.compute_sync_committee_period(
        spec.get_current_epoch(state)) \
        != spec.compute_sync_committee_period(next_slot_epoch)
    _check_subnets_against_committee(spec, state,
                                     state.next_sync_committee)


@with_all_phases_from("altair")
@spec_state_test
@no_vectors
@always_bls
def test_get_sync_committee_selection_proof(spec, state):
    slot = uint64(1)
    subcommittee_index = uint64(0)
    privkey = privkeys[1]
    proof = spec.get_sync_committee_selection_proof(
        state, slot, subcommittee_index, privkey)
    domain = spec.get_domain(
        state, spec.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
        spec.compute_epoch_at_slot(slot))
    signing_data = spec.SyncAggregatorSelectionData(
        slot=slot, subcommittee_index=subcommittee_index)
    signing_root = spec.compute_signing_root(signing_data, domain)
    assert bls.Verify(pubkeys[1], signing_root, proof)


@with_all_phases_from("altair")
@with_presets(["mainnet"],
              reason="statistical check needs the mainnet committee size")
@spec_state_test
@no_vectors
def test_is_sync_committee_aggregator(spec, state):
    sample_count = (int(spec.SYNC_COMMITTEE_SIZE)
                    // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)) * 100
    is_aggregator_count = 0
    for i in range(sample_count):
        signature = spec.hash(i.to_bytes(32, byteorder="little"))
        if spec.is_sync_committee_aggregator(signature):
            is_aggregator_count += 1
    target = int(spec.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE) * 100
    assert target * 0.9 <= is_aggregator_count <= target * 1.1


@with_all_phases_from("altair")
@spec_state_test
@no_vectors
def test_get_contribution_and_proof(spec, state):
    aggregator_index = uint64(10)
    privkey = privkeys[3]
    subcommittee_size = int(spec.SYNC_COMMITTEE_SIZE) \
        // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    contribution = spec.SyncCommitteeContribution(
        slot=uint64(10),
        beacon_block_root=b"\x12" * 32,
        subcommittee_index=uint64(1),
        aggregation_bits=[False] * subcommittee_size,
        signature=b"\x32" * 96)
    selection_proof = spec.get_sync_committee_selection_proof(
        state, contribution.slot, contribution.subcommittee_index,
        privkey)
    contribution_and_proof = spec.get_contribution_and_proof(
        state, aggregator_index, contribution, privkey)
    assert contribution_and_proof == spec.ContributionAndProof(
        aggregator_index=aggregator_index,
        contribution=contribution,
        selection_proof=selection_proof)


@with_all_phases_from("altair")
@spec_state_test
@no_vectors
@always_bls
def test_get_contribution_and_proof_signature(spec, state):
    privkey = privkeys[3]
    subcommittee_size = int(spec.SYNC_COMMITTEE_SIZE) \
        // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    contribution_and_proof = spec.ContributionAndProof(
        aggregator_index=uint64(10),
        contribution=spec.SyncCommitteeContribution(
            slot=uint64(10),
            beacon_block_root=b"\x12" * 32,
            subcommittee_index=uint64(1),
            aggregation_bits=[False] * subcommittee_size,
            signature=b"\x34" * 96),
        selection_proof=b"\x56" * 96)
    signature = spec.get_contribution_and_proof_signature(
        state, contribution_and_proof, privkey)
    domain = spec.get_domain(
        state, spec.DOMAIN_CONTRIBUTION_AND_PROOF,
        spec.compute_epoch_at_slot(
            contribution_and_proof.contribution.slot))
    signing_root = spec.compute_signing_root(contribution_and_proof,
                                             domain)
    assert bls.Verify(pubkeys[3], signing_root, signature)
