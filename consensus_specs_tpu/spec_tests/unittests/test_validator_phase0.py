"""Phase0 validator-duty unit battery (reference
test/phase0/unittests/validator/test_validator_unittest.py, 24 defs):
signing helpers, committee assignment, eth1 voting, aggregation
selection, subnet computation — asserted directly against
specs/validator_duties.py."""
import random

from ...ssz import hash_tree_root, uint64
from ...test_infra.context import (
    spec_state_test, spec_test, no_vectors, with_all_phases, always_bls)
from ...test_infra.attestations import get_valid_attestation
from ...test_infra.blocks import (
    build_empty_block, build_empty_block_for_next_slot, next_epoch)
from ...test_infra.keys import privkeys, pubkeys, pubkey_of
from ...utils import bls


def _run_get_signature_test(spec, state, domain, signature,
                            signing_ssz_object, privkey):
    signing_root = spec.compute_signing_root(signing_ssz_object, domain)
    assert bls.Verify(pubkey_of(privkey), signing_root, signature)


def _min_new_period_epochs(spec) -> int:
    return ((int(spec.config.SECONDS_PER_ETH1_BLOCK)
             * int(spec.config.ETH1_FOLLOW_DISTANCE) * 2)
            // int(spec.config.SECONDS_PER_SLOT)
            // int(spec.SLOTS_PER_EPOCH))


def _mock_aggregate(spec):
    return spec.Attestation(data=spec.AttestationData(slot=uint64(10)))


# --- becoming a validator -------------------------------------------------

@with_all_phases
@spec_state_test
@no_vectors
def test_check_if_validator_active(spec, state):
    active_index = 0
    assert spec.check_if_validator_active(state, active_index)
    # a fresh deposit is not active yet
    new_index = len(state.validators)
    validator = state.validators[0].copy()
    validator.activation_epoch = spec.FAR_FUTURE_EPOCH
    validator.activation_eligibility_epoch = spec.FAR_FUTURE_EPOCH
    state.validators.append(validator)
    state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    assert not spec.check_if_validator_active(state, new_index)


# --- committee assignment -------------------------------------------------

def _run_get_committee_assignment(spec, state, epoch, validator_index,
                                  valid=True):
    try:
        committee, committee_index, slot = spec.get_committee_assignment(
            state, epoch, validator_index)
        assert int(spec.compute_epoch_at_slot(slot)) == int(epoch)
        assert list(committee) == list(spec.get_beacon_committee(
            state, slot, committee_index))
        assert int(committee_index) < int(
            spec.get_committee_count_per_slot(state, epoch))
        assert validator_index in committee
        assert valid
    except AssertionError:
        assert not valid


@with_all_phases
@spec_state_test
@no_vectors
def test_get_committee_assignment_current_epoch(spec, state):
    _run_get_committee_assignment(
        spec, state, spec.get_current_epoch(state), 0)


@with_all_phases
@spec_state_test
@no_vectors
def test_get_committee_assignment_next_epoch(spec, state):
    _run_get_committee_assignment(
        spec, state, spec.get_current_epoch(state) + 1, 0)


@with_all_phases
@spec_state_test
@no_vectors
def test_get_committee_assignment_out_bound_epoch(spec, state):
    _run_get_committee_assignment(
        spec, state, spec.get_current_epoch(state) + 2, 0, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
def test_is_proposer(spec, state):
    proposer_index = spec.get_beacon_proposer_index(state)
    assert spec.is_proposer(state, proposer_index)
    proposer_index = (proposer_index + 1) % len(state.validators)
    assert not spec.is_proposer(state, proposer_index)


# --- block proposal signatures -------------------------------------------

@with_all_phases
@spec_state_test
@no_vectors
@always_bls
def test_get_epoch_signature(spec, state):
    block = spec.BeaconBlock()
    privkey = privkeys[0]
    signature = spec.get_epoch_signature(state, block, privkey)
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO,
                             spec.compute_epoch_at_slot(block.slot))
    _run_get_signature_test(
        spec, state, domain, signature,
        uint64(spec.compute_epoch_at_slot(block.slot)), privkey)


@with_all_phases
@spec_state_test
@no_vectors
@always_bls
def test_get_block_signature(spec, state):
    privkey = privkeys[0]
    block = build_empty_block_for_next_slot(spec, state)
    signature = spec.get_block_signature(state, block, privkey)
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER,
                             spec.compute_epoch_at_slot(block.slot))
    _run_get_signature_test(spec, state, domain, signature, block,
                            privkey)


# --- eth1 voting ----------------------------------------------------------

def _run_is_candidate_block(spec, eth1_block, period_start,
                            success=True):
    assert success == spec.is_candidate_block(eth1_block, period_start)


@with_all_phases
@spec_state_test
@no_vectors
def test_is_candidate_block(spec, state):
    distance = int(spec.config.SECONDS_PER_ETH1_BLOCK) \
        * int(spec.config.ETH1_FOLLOW_DISTANCE)
    period_start = distance * 2 + 1000
    _run_is_candidate_block(
        spec, spec.Eth1Block(timestamp=period_start - distance),
        period_start, success=True)
    _run_is_candidate_block(
        spec, spec.Eth1Block(timestamp=period_start - distance + 1),
        period_start, success=False)
    _run_is_candidate_block(
        spec, spec.Eth1Block(timestamp=period_start - distance * 2),
        period_start, success=True)
    _run_is_candidate_block(
        spec, spec.Eth1Block(timestamp=period_start - distance * 2 - 1),
        period_start, success=False)


@with_all_phases
@spec_state_test
@no_vectors
def test_get_eth1_vote_default_vote(spec, state):
    for _ in range(_min_new_period_epochs(spec)):
        next_epoch(spec, state)
    state.eth1_data_votes = type(state.eth1_data_votes)()
    assert spec.get_eth1_vote(state, []) == state.eth1_data


@with_all_phases
@spec_state_test
@no_vectors
def test_get_eth1_vote_consensus_vote(spec, state):
    for _ in range(_min_new_period_epochs(spec) + 2):
        next_epoch(spec, state)
    period_start = spec.voting_period_start_time(state)
    votes_length = int(spec.get_current_epoch(state)) \
        % int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD)
    assert votes_length >= 3
    state.eth1_data_votes = type(state.eth1_data_votes)()
    follow = int(spec.config.SECONDS_PER_ETH1_BLOCK) \
        * int(spec.config.ETH1_FOLLOW_DISTANCE)
    block_1 = spec.Eth1Block(
        timestamp=int(period_start) - follow - 1,
        deposit_count=state.eth1_data.deposit_count,
        deposit_root=b"\x04" * 32)
    block_2 = spec.Eth1Block(
        timestamp=int(period_start) - follow,
        deposit_count=int(state.eth1_data.deposit_count) + 1,
        deposit_root=b"\x05" * 32)
    eth1_chain = [block_1, block_2]
    votes = [spec.get_eth1_data(block_1)]
    votes += [spec.get_eth1_data(block_2)] * (votes_length - 1)
    state.eth1_data_votes = votes
    eth1_data = spec.get_eth1_vote(state, eth1_chain)
    assert eth1_data.block_hash == hash_tree_root(block_2)


@with_all_phases
@spec_state_test
@no_vectors
def test_get_eth1_vote_tie(spec, state):
    for _ in range(_min_new_period_epochs(spec) + 1):
        next_epoch(spec, state)
    period_start = spec.voting_period_start_time(state)
    votes_length = int(spec.get_current_epoch(state)) \
        % int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD)
    assert votes_length > 0 and votes_length % 2 == 0
    state.eth1_data_votes = type(state.eth1_data_votes)()
    follow = int(spec.config.SECONDS_PER_ETH1_BLOCK) \
        * int(spec.config.ETH1_FOLLOW_DISTANCE)
    block_1 = spec.Eth1Block(
        timestamp=int(period_start) - follow - 1,
        deposit_count=state.eth1_data.deposit_count,
        deposit_root=b"\x04" * 32)
    block_2 = spec.Eth1Block(
        timestamp=int(period_start) - follow,
        deposit_count=int(state.eth1_data.deposit_count) + 1,
        deposit_root=b"\x05" * 32)
    eth1_chain = [block_1, block_2]
    votes = [spec.get_eth1_data(block_1 if i % 2 == 0 else block_2)
             for i in range(votes_length)]
    state.eth1_data_votes = votes
    eth1_data = spec.get_eth1_vote(state, eth1_chain)
    # tiebreak: the earliest vote wins -> block_1
    assert eth1_data.block_hash == hash_tree_root(eth1_chain[0])


@with_all_phases
@spec_state_test
@no_vectors
def test_get_eth1_vote_chain_in_past(spec, state):
    for _ in range(_min_new_period_epochs(spec) + 1):
        next_epoch(spec, state)
    period_start = spec.voting_period_start_time(state)
    follow = int(spec.config.SECONDS_PER_ETH1_BLOCK) \
        * int(spec.config.ETH1_FOLLOW_DISTANCE)
    block_1 = spec.Eth1Block(
        timestamp=int(period_start) - follow,
        deposit_count=int(state.eth1_data.deposit_count) - 1,
        deposit_root=b"\x42" * 32)
    state.eth1_data_votes = type(state.eth1_data_votes)()
    # a chain behind the current eth1 data is never a candidate
    assert spec.get_eth1_vote(state, [block_1]) == state.eth1_data


@with_all_phases
@spec_state_test
@no_vectors
def test_compute_new_state_root(spec, state):
    pre_state = state.copy()
    post_state = state.copy()
    block = build_empty_block(spec, state, uint64(int(state.slot) + 1))
    state_root = spec.compute_new_state_root(state, block)
    assert state_root != hash_tree_root(pre_state)
    assert state == pre_state  # input state untouched
    # matches the actual transition
    signed = spec.SignedBeaconBlock(message=block)
    spec.state_transition(post_state, signed, validate_result=False)
    assert state_root == hash_tree_root(post_state)


# --- fork digest / subnets ------------------------------------------------

@with_all_phases
@spec_state_test
@no_vectors
def test_compute_fork_digest(spec, state):
    actual = spec.compute_fork_digest(state.fork.current_version,
                                      state.genesis_validators_root)
    expected = bytes(spec.compute_fork_data_root(
        state.fork.current_version,
        state.genesis_validators_root))[:4]
    assert bytes(actual) == expected


@with_all_phases
@spec_state_test
@no_vectors
def test_compute_subnet_for_attestation(spec, state):
    for committee_idx in range(
            int(spec.get_committee_count_per_slot(
                state, spec.get_current_epoch(state)))):
        actual = spec.compute_subnet_for_attestation(
            spec.get_committee_count_per_slot(
                state, spec.get_current_epoch(state)),
            state.slot, committee_idx)
        committees_per_slot = int(spec.get_committee_count_per_slot(
            state, spec.get_current_epoch(state)))
        slots_since_epoch_start = int(state.slot) \
            % int(spec.SLOTS_PER_EPOCH)
        expected = (committees_per_slot * slots_since_epoch_start
                    + committee_idx) \
            % int(spec.ATTESTATION_SUBNET_COUNT)
        assert int(actual) == expected


# --- attestation signatures & aggregation ---------------------------------

@with_all_phases
@spec_state_test
@no_vectors
@always_bls
def test_get_attestation_signature_phase0(spec, state):
    privkey = privkeys[0]
    attestation_data = spec.AttestationData(slot=uint64(10))
    signature = spec.get_attestation_signature(
        state, attestation_data, privkey)
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                             attestation_data.target.epoch)
    _run_get_signature_test(spec, state, domain, signature,
                            attestation_data, privkey)


@with_all_phases
@spec_state_test
@no_vectors
@always_bls
def test_get_slot_signature(spec, state):
    privkey = privkeys[0]
    slot = uint64(10)
    signature = spec.get_slot_signature(state, slot, privkey)
    domain = spec.get_domain(state, spec.DOMAIN_SELECTION_PROOF,
                             spec.compute_epoch_at_slot(slot))
    _run_get_signature_test(spec, state, domain, signature, slot,
                            privkey)


@with_all_phases
@spec_state_test
@no_vectors
@always_bls
def test_is_aggregator(spec, state):
    # at least one committee member is selected as aggregator
    slot = state.slot
    committee_index = 0
    has_aggregator = False
    committee = spec.get_beacon_committee(state, slot, committee_index)
    for validator_index in committee:
        privkey = privkeys[pubkeys.index(
            bytes(state.validators[validator_index].pubkey))]
        slot_signature = spec.get_slot_signature(state, slot, privkey)
        if spec.is_aggregator(state, slot, committee_index,
                              slot_signature):
            has_aggregator = True
            break
    assert has_aggregator


@with_all_phases
@spec_state_test
@no_vectors
@always_bls
def test_get_aggregate_signature(spec, state):
    attestations = []
    attesting_pubkeys = []
    slot = state.slot
    committee_index = 0
    attestation_data = spec.AttestationData(
        slot=slot, index=committee_index)
    committee = spec.get_beacon_committee(state, slot, committee_index)
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                             attestation_data.target.epoch)
    signing_root = spec.compute_signing_root(attestation_data, domain)
    for i, validator_index in enumerate(committee):
        bits = [False] * len(committee)
        bits[i] = True
        privkey = privkeys[pubkeys.index(
            bytes(state.validators[validator_index].pubkey))]
        attestation = spec.Attestation(
            data=attestation_data,
            aggregation_bits=bits,
            signature=bls.Sign(privkey, signing_root))
        attestations.append(attestation)
        attesting_pubkeys.append(
            bytes(state.validators[validator_index].pubkey))
    assert len(attestations) > 0
    signature = spec.get_aggregate_signature(attestations)
    assert bls.FastAggregateVerify(attesting_pubkeys, signing_root,
                                   signature)


@with_all_phases
@spec_state_test
@no_vectors
def test_get_aggregate_and_proof(spec, state):
    privkey = privkeys[0]
    aggregator_index = uint64(10)
    aggregate = _mock_aggregate(spec)
    aggregate_and_proof = spec.get_aggregate_and_proof(
        state, aggregator_index, aggregate, privkey)
    assert aggregate_and_proof.aggregator_index == aggregator_index
    assert aggregate_and_proof.aggregate == aggregate
    assert aggregate_and_proof.selection_proof == \
        spec.get_slot_signature(state, aggregate.data.slot, privkey)


@with_all_phases
@spec_state_test
@no_vectors
@always_bls
def test_get_aggregate_and_proof_signature(spec, state):
    privkey = privkeys[0]
    aggregate = _mock_aggregate(spec)
    aggregate_and_proof = spec.get_aggregate_and_proof(
        state, uint64(10), aggregate, privkey)
    signature = spec.get_aggregate_and_proof_signature(
        state, aggregate_and_proof, privkey)
    domain = spec.get_domain(
        state, spec.DOMAIN_AGGREGATE_AND_PROOF,
        spec.compute_epoch_at_slot(aggregate.data.slot))
    _run_get_signature_test(spec, state, domain, signature,
                            aggregate_and_proof, privkey)


# --- subscribed subnets ---------------------------------------------------

def _run_compute_subscribed_subnets_arguments(spec, rng):
    node_id = rng.randint(0, 2**256 - 1)
    epoch = rng.randint(0, 2**64 - 1)
    subnets = spec.compute_subscribed_subnets(node_id, epoch)
    assert len(subnets) == int(spec.config.SUBNETS_PER_NODE)
    for subnet in subnets:
        assert 0 <= int(subnet) < int(spec.config.ATTESTATION_SUBNET_COUNT)


@with_all_phases
@spec_test
@no_vectors
def test_compute_subscribed_subnets_random_1(spec):
    _run_compute_subscribed_subnets_arguments(spec, random.Random(1111))


@with_all_phases
@spec_test
@no_vectors
def test_compute_subscribed_subnets_random_2(spec):
    _run_compute_subscribed_subnets_arguments(spec, random.Random(2222))


@with_all_phases
@spec_test
@no_vectors
def test_compute_subscribed_subnets_random_3(spec):
    _run_compute_subscribed_subnets_arguments(spec, random.Random(3333))
