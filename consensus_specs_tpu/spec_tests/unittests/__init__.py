"""Pure-assert unit batteries (reference test/*/unittests/): config
invariants, helper/validator-duty units, fork-choice handler units.
These never emit conformance vectors (every test is @no_vectors) — they
exist to localize constant/helper regressions the trajectory suites can
only detect, not attribute."""
