"""Config/preset invariant units (reference
test/phase0/unittests/test_config_invariants.py + the altair, deneb,
electra, fulu and whisk per-fork variants).  Pure asserts over the
baked constants — no state transitions, no vectors."""
from ...test_infra.context import (
    spec_state_test, spec_test, no_vectors, with_all_phases,
    with_all_phases_from)

UINT64_MAX = 2**64 - 1


def _check_bound(value, lower, upper) -> None:
    assert lower <= value <= upper


# ----------------------------------------------------------------------
# phase0 (reference test_config_invariants.py: 7 defs)
# ----------------------------------------------------------------------

@with_all_phases
@spec_state_test
@no_vectors
def test_validators(spec, state):
    _check_bound(spec.VALIDATOR_REGISTRY_LIMIT, 1, UINT64_MAX)
    _check_bound(spec.MAX_COMMITTEES_PER_SLOT, 1, UINT64_MAX)
    _check_bound(spec.TARGET_COMMITTEE_SIZE, 1, UINT64_MAX)
    maximum_validators_per_committee = (
        spec.VALIDATOR_REGISTRY_LIMIT
        // spec.SLOTS_PER_EPOCH
        // spec.MAX_COMMITTEES_PER_SLOT)
    _check_bound(spec.MAX_VALIDATORS_PER_COMMITTEE, 1,
                 maximum_validators_per_committee)
    _check_bound(spec.config.MIN_PER_EPOCH_CHURN_LIMIT, 1,
                 spec.VALIDATOR_REGISTRY_LIMIT)
    _check_bound(spec.config.CHURN_LIMIT_QUOTIENT, 1,
                 spec.VALIDATOR_REGISTRY_LIMIT)
    _check_bound(spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT,
                 spec.TARGET_COMMITTEE_SIZE, UINT64_MAX)


@with_all_phases
@spec_state_test
@no_vectors
def test_balances(spec, state):
    assert spec.MAX_EFFECTIVE_BALANCE \
        % spec.EFFECTIVE_BALANCE_INCREMENT == 0
    _check_bound(spec.MIN_DEPOSIT_AMOUNT, 1, UINT64_MAX)
    _check_bound(spec.MAX_EFFECTIVE_BALANCE, spec.MIN_DEPOSIT_AMOUNT,
                 UINT64_MAX)
    _check_bound(spec.MAX_EFFECTIVE_BALANCE,
                 spec.EFFECTIVE_BALANCE_INCREMENT, UINT64_MAX)


@with_all_phases
@spec_state_test
@no_vectors
def test_hysteresis_quotient(spec, state):
    _check_bound(spec.HYSTERESIS_QUOTIENT, 1, UINT64_MAX)
    _check_bound(spec.HYSTERESIS_DOWNWARD_MULTIPLIER, 1,
                 spec.HYSTERESIS_QUOTIENT)
    _check_bound(spec.HYSTERESIS_UPWARD_MULTIPLIER,
                 spec.HYSTERESIS_QUOTIENT, UINT64_MAX)


@with_all_phases
@spec_state_test
@no_vectors
def test_incentives(spec, state):
    # no ETH is minted in slash_validator
    if spec.is_post("electra"):
        assert spec.MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA \
            <= spec.WHISTLEBLOWER_REWARD_QUOTIENT_ELECTRA
    elif spec.is_post("bellatrix"):
        assert spec.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX \
            <= spec.WHISTLEBLOWER_REWARD_QUOTIENT
    elif spec.is_post("altair"):
        assert spec.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR \
            <= spec.WHISTLEBLOWER_REWARD_QUOTIENT
    else:
        assert spec.MIN_SLASHING_PENALTY_QUOTIENT \
            <= spec.WHISTLEBLOWER_REWARD_QUOTIENT


@with_all_phases
@spec_state_test
@no_vectors
def test_time(spec, state):
    assert spec.SLOTS_PER_EPOCH <= spec.SLOTS_PER_HISTORICAL_ROOT
    assert spec.MIN_SEED_LOOKAHEAD < spec.MAX_SEED_LOOKAHEAD
    assert spec.SLOTS_PER_HISTORICAL_ROOT % spec.SLOTS_PER_EPOCH == 0
    _check_bound(spec.SLOTS_PER_HISTORICAL_ROOT, spec.SLOTS_PER_EPOCH,
                 UINT64_MAX)
    _check_bound(spec.MIN_ATTESTATION_INCLUSION_DELAY, 1,
                 spec.SLOTS_PER_EPOCH)


@with_all_phases
@spec_state_test
@no_vectors
def test_networking(spec, state):
    assert spec.config.MIN_EPOCHS_FOR_BLOCK_REQUESTS == (
        spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        + spec.config.CHURN_LIMIT_QUOTIENT // 2)
    ceillog2_subnets = (int(spec.config.ATTESTATION_SUBNET_COUNT)
                        - 1).bit_length()
    assert spec.config.ATTESTATION_SUBNET_PREFIX_BITS == (
        ceillog2_subnets + spec.config.ATTESTATION_SUBNET_EXTRA_BITS)
    assert spec.config.SUBNETS_PER_NODE \
        <= spec.config.ATTESTATION_SUBNET_COUNT
    assert spec.NODE_ID_BITS == 256


@with_all_phases
@spec_state_test
@no_vectors
def test_fork_choice(spec, state):
    assert spec.INTERVALS_PER_SLOT < spec.config.SECONDS_PER_SLOT
    assert spec.config.PROPOSER_SCORE_BOOST <= 100


# ----------------------------------------------------------------------
# altair (reference test/altair/unittests/test_config_invariants.py)
# ----------------------------------------------------------------------

@with_all_phases_from("altair")
@spec_test
@no_vectors
def test_weight_denominator(spec):
    assert (spec.TIMELY_HEAD_WEIGHT + spec.TIMELY_SOURCE_WEIGHT
            + spec.TIMELY_TARGET_WEIGHT + spec.SYNC_REWARD_WEIGHT
            + spec.PROPOSER_WEIGHT) == spec.WEIGHT_DENOMINATOR


@with_all_phases_from("altair")
@spec_test
@no_vectors
def test_inactivity_score(spec):
    # leaks must decay no slower than they accrue
    assert spec.config.INACTIVITY_SCORE_BIAS \
        <= spec.config.INACTIVITY_SCORE_RECOVERY_RATE \
        * spec.config.INACTIVITY_SCORE_BIAS


# ----------------------------------------------------------------------
# deneb (reference test/deneb/unittests/test_config_invariants.py)
# ----------------------------------------------------------------------

@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_blob_bounds(spec):
    assert int(spec.config.MAX_BLOBS_PER_BLOCK) \
        <= int(spec.MAX_BLOB_COMMITMENTS_PER_BLOCK)


@with_all_phases_from("deneb")
@spec_test
@no_vectors
def test_blob_fields(spec):
    assert int(spec.FIELD_ELEMENTS_PER_BLOB) \
        * int(spec.BYTES_PER_FIELD_ELEMENT) == int(spec.BYTES_PER_BLOB)


# ----------------------------------------------------------------------
# electra (reference test/electra/unittests/test_config_invariants.py)
# ----------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_electra_churn(spec):
    assert int(spec.config.MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA) \
        <= int(spec.config.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT)


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_electra_balances(spec):
    assert int(spec.MIN_ACTIVATION_BALANCE) \
        <= int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    assert int(spec.MIN_ACTIVATION_BALANCE) \
        % int(spec.EFFECTIVE_BALANCE_INCREMENT) == 0
    assert int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA) \
        % int(spec.EFFECTIVE_BALANCE_INCREMENT) == 0


# ----------------------------------------------------------------------
# fulu (reference test/fulu/unittests/test_config_invariants.py)
# ----------------------------------------------------------------------

@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_custody_groups_bound(spec):
    assert int(spec.config.CUSTODY_REQUIREMENT) \
        <= int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    assert int(spec.config.NUMBER_OF_CUSTODY_GROUPS) \
        <= int(spec.config.NUMBER_OF_COLUMNS)
    assert int(spec.config.NUMBER_OF_COLUMNS) \
        % int(spec.config.NUMBER_OF_CUSTODY_GROUPS) == 0


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_columns_match_cells(spec):
    # the extended matrix splits evenly into columns
    assert int(spec.CELLS_PER_EXT_BLOB) \
        == int(spec.config.NUMBER_OF_COLUMNS)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_sampling_bound(spec):
    assert int(spec.config.SAMPLES_PER_SLOT) \
        <= int(spec.config.NUMBER_OF_COLUMNS)
