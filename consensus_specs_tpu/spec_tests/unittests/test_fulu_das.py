"""Fulu DAS unit battery (reference
test/fulu/unittests/das/test_das.py, 9 defs): extended-matrix
construction/recovery and the extended-sample-count bound.

Matrix tests run on a FRESH FuluSpec with a small insecure dev KZG
sampling engine (width 128 — the pattern of tests/test_fulu.py), so the
pure-Python erasure code stays fast while the spec methods under test
are the real ones."""
import random

from ...crypto.fields import R as BLS_MODULUS
from ...crypto.kzg_sampling import KZGSampling
from ...test_infra.context import (
    spec_test, no_vectors, with_all_phases_from, with_config_overrides)
from ...utils.kzg_setup_gen import generate_setup

_DEV_WIDTH = 128
_dev_engine = None


def _dev_spec():
    """Fresh minimal FuluSpec with the shared dev sampling engine."""
    global _dev_engine
    from ...specs.fulu import FuluSpec
    if _dev_engine is None:
        _dev_engine = KZGSampling(_DEV_WIDTH, 64,
                                  setup=generate_setup(_DEV_WIDTH))
    spec = FuluSpec("minimal")
    spec._kzg_sampling = _dev_engine
    return spec


def _dev_blob(rng):
    return b"".join(rng.randrange(BLS_MODULUS).to_bytes(32, "big")
                    for _ in range(_DEV_WIDTH))


def _chunks(lst, n):
    return [lst[i:i + n] for i in range(0, len(lst), n)]


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_compute_matrix(spec):
    rng = random.Random(5566)
    spec = _dev_spec()
    cells_per_ext_blob = spec._kzg_sampling.cells_per_ext_blob
    blob_count = 2
    input_blobs = [_dev_blob(rng) for _ in range(blob_count)]
    matrix = spec.compute_matrix(input_blobs)
    assert len(matrix) == cells_per_ext_blob * blob_count
    rows = _chunks(matrix, cells_per_ext_blob)
    assert len(rows) == blob_count
    for row in rows:
        assert len(row) == cells_per_ext_blob
    for blob_index, row in enumerate(rows):
        extended_blob = []
        for entry in row:
            extended_blob.extend(spec.cell_to_coset_evals(
                bytes(entry.cell)))
        blob_part = extended_blob[0:len(extended_blob) // 2]
        blob = b"".join(x.to_bytes(32, "big") for x in blob_part)
        assert blob == input_blobs[blob_index]


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_recover_matrix(spec):
    rng = random.Random(5566)
    spec = _dev_spec()
    cells_per_ext_blob = spec._kzg_sampling.cells_per_ext_blob
    n_samples = cells_per_ext_blob // 2
    blob_count = 2
    blobs = [_dev_blob(rng) for _ in range(blob_count)]
    matrix = spec.compute_matrix(blobs)
    partial_matrix = []
    for blob_entries in _chunks(matrix, cells_per_ext_blob):
        rng.shuffle(blob_entries)
        partial_matrix.extend(blob_entries[:n_samples])
    recovered = spec.recover_matrix(partial_matrix, blob_count)
    key = lambda e: (int(e.row_index), int(e.column_index))  # noqa: E731
    assert sorted(map(key, recovered)) == sorted(map(key, matrix))
    by_key = {key(e): e for e in matrix}
    for e in recovered:
        assert bytes(e.cell) == bytes(by_key[key(e)].cell)
        assert bytes(e.kzg_proof) == bytes(by_key[key(e)].kzg_proof)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_extended_sample_count__1(spec):
    rng = random.Random(1111)
    allowed_failures = rng.randint(
        0, int(spec.config.NUMBER_OF_COLUMNS) // 2)
    spec.get_extended_sample_count(allowed_failures)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_extended_sample_count__2(spec):
    rng = random.Random(2222)
    allowed_failures = rng.randint(
        0, int(spec.config.NUMBER_OF_COLUMNS) // 2)
    spec.get_extended_sample_count(allowed_failures)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_extended_sample_count__3(spec):
    rng = random.Random(3333)
    allowed_failures = rng.randint(
        0, int(spec.config.NUMBER_OF_COLUMNS) // 2)
    spec.get_extended_sample_count(allowed_failures)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_extended_sample_count__lower_bound(spec):
    spec.get_extended_sample_count(0)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_extended_sample_count__upper_bound(spec):
    spec.get_extended_sample_count(
        int(spec.config.NUMBER_OF_COLUMNS) // 2)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_get_extended_sample_count__upper_bound_exceed(spec):
    try:
        spec.get_extended_sample_count(
            int(spec.config.NUMBER_OF_COLUMNS) // 2 + 1)
        raise RuntimeError("out-of-bound allowed_failures accepted")
    except AssertionError:
        pass


@with_all_phases_from("fulu")
@with_config_overrides({"NUMBER_OF_COLUMNS": 128,
                        "SAMPLES_PER_SLOT": 16})
@spec_test
@no_vectors
def test_get_extended_sample_count__table_in_spec(spec):
    # the worked table from fulu/peer-sampling.md
    table = {0: 16, 1: 20, 2: 24, 3: 27, 4: 29,
             5: 32, 6: 35, 7: 37, 8: 40}
    for allowed_failures, expected in table.items():
        assert int(spec.get_extended_sample_count(allowed_failures)) \
            == expected
