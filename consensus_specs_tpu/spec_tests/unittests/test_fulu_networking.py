"""Fulu data-column sidecar networking unit battery (reference
test/fulu/unittests/test_networking.py, 14 defs): structural sidecar
validation, column KZG batch proofs, commitment inclusion proofs,
sidecar subnet mapping.

Sidecars are built on a FRESH FuluSpec with the small dev sampling
engine (width 128) and its column count shrunk to match — the sidecar
container shapes and merkle machinery are the real ones."""
import random

from ...crypto.fields import R as BLS_MODULUS
from ...crypto.kzg_sampling import KZGSampling
from ...debug.random_value import RandomizationMode, get_random_ssz_object
from ...ssz import uint64
from ...test_infra.context import (
    spec_test, no_vectors, with_all_phases_from)
from ...utils.kzg_setup_gen import generate_setup

_DEV_WIDTH = 128
_dev_engine = None


def _dev_spec():
    global _dev_engine
    from ...specs.fulu import FuluSpec
    if _dev_engine is None:
        _dev_engine = KZGSampling(_DEV_WIDTH, 64,
                                  setup=generate_setup(_DEV_WIDTH))
    spec = FuluSpec("minimal")
    spec._kzg_sampling = _dev_engine
    # column fan-out must match the dev engine's extended-blob shape
    spec.config = spec.config.replace(
        NUMBER_OF_COLUMNS=_dev_engine.cells_per_ext_blob)
    return spec


def _compute_data_column_sidecar(spec):
    """A sidecar from a chaos-random block carrying two real (dev-width)
    blob commitments (reference compute_data_column_sidecar shape)."""
    rng = random.Random(5566)
    blobs = [b"".join(rng.randrange(BLS_MODULUS).to_bytes(32, "big")
                      for _ in range(_DEV_WIDTH)) for _ in range(2)]
    commitments = [spec._kzg_sampling.blob_to_kzg_commitment(b)
                   for b in blobs]
    block = get_random_ssz_object(
        rng, spec.BeaconBlock, max_bytes_length=2000,
        max_list_length=2000, mode=RandomizationMode.RANDOM,
        chaos=True)
    block.body.blob_kzg_commitments = [bytes(c) for c in commitments]
    signed_block = spec.SignedBeaconBlock(message=block,
                                          signature=b"\x11" * 96)
    cells_and_kzg_proofs = [
        spec.compute_cells_and_kzg_proofs(blob) for blob in blobs]
    return spec.get_data_column_sidecars(signed_block,
                                         cells_and_kzg_proofs)[0]


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar__valid(spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    assert spec.verify_data_column_sidecar(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar__invalid_zero_blobs(spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.column = []
    sidecar.kzg_commitments = []
    sidecar.kzg_proofs = []
    assert not spec.verify_data_column_sidecar(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar__invalid_index(spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.index = 128
    assert not spec.verify_data_column_sidecar(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar__invalid_mismatch_len_column(spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.column = sidecar.column[1:]
    assert not spec.verify_data_column_sidecar(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar__invalid_mismatch_len_kzg_commitments(
        spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.kzg_commitments = sidecar.kzg_commitments[1:]
    assert not spec.verify_data_column_sidecar(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecars__invalid_mismatch_len_kzg_proofs(
        spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.kzg_proofs = sidecar.kzg_proofs[1:]
    assert not spec.verify_data_column_sidecar(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar_kzg_proofs__valid(spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    assert spec.verify_data_column_sidecar_kzg_proofs(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar_kzg_proofs__invalid_wrong_column(
        spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.column[0] = sidecar.column[1]
    assert not spec.verify_data_column_sidecar_kzg_proofs(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar_kzg_proofs__invalid_wrong_commitment(
        spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.kzg_commitments[0] = sidecar.kzg_commitments[1]
    assert not spec.verify_data_column_sidecar_kzg_proofs(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar_kzg_proofs__invalid_wrong_proof(spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.kzg_proofs[0] = sidecar.kzg_proofs[1]
    assert not spec.verify_data_column_sidecar_kzg_proofs(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar_inclusion_proof__valid(spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    assert spec.verify_data_column_sidecar_inclusion_proof(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar_inclusion_proof__invalid_missing_commitment(
        spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.kzg_commitments = sidecar.kzg_commitments[1:]
    assert not spec.verify_data_column_sidecar_inclusion_proof(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_verify_data_column_sidecar_inclusion_proof__invalid_duplicate_commitment(
        spec):
    spec = _dev_spec()
    sidecar = _compute_data_column_sidecar(spec)
    sidecar.kzg_commitments = list(sidecar.kzg_commitments) \
        + [sidecar.kzg_commitments[0]]
    assert not spec.verify_data_column_sidecar_inclusion_proof(sidecar)


@with_all_phases_from("fulu")
@spec_test
@no_vectors
def test_compute_subnet_for_data_column_sidecar(spec):
    subnet_results = []
    for column_index in range(
            int(spec.config.DATA_COLUMN_SIDECAR_SUBNET_COUNT)):
        subnet = spec.compute_subnet_for_data_column_sidecar(
            uint64(column_index))
        assert int(subnet) \
            < int(spec.config.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
        subnet_results.append(int(subnet))
    # no duplicates within one subnet-count span
    assert len(subnet_results) == len(set(subnet_results))
