"""on_attestation unit battery (reference
test/phase0/unittests/fork_choice/test_on_attestation.py, 13 defs):
latest-message bookkeeping plus every rejection path of
validate_on_attestation, asserted directly on the store."""
from ...ssz import hash_tree_root
from ...test_infra.context import (
    spec_state_test, no_vectors, with_all_phases, never_bls)
from ...test_infra.attestations import (
    get_valid_attestation, sign_attestation)
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, next_epoch, next_slot,
    state_transition_and_sign_block, transition_to)
from ...test_infra.fork_choice import get_genesis_forkchoice_store


def _run_on_attestation(spec, state, store, attestation, valid=True):
    if not valid:
        try:
            spec.on_attestation(store, attestation)
        except (AssertionError, KeyError, ValueError, IndexError):
            return
        raise AssertionError("attestation unexpectedly valid")
    indexed = spec.get_indexed_attestation(state, attestation)
    spec.on_attestation(store, attestation)
    sample_index = indexed.attesting_indices[0]
    latest = store.latest_messages[sample_index]
    assert int(latest.epoch) == int(attestation.data.target.epoch)
    assert latest.root == attestation.data.beacon_block_root


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_current_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT) * 2)
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    attestation = get_valid_attestation(spec, state, slot=block.slot,
                                        signed=True)
    assert int(attestation.data.target.epoch) == int(spec.GENESIS_EPOCH)
    _run_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_previous_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT)
                 * int(spec.SLOTS_PER_EPOCH))
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    attestation = get_valid_attestation(spec, state, slot=block.slot,
                                        signed=True)
    assert int(attestation.data.target.epoch) == int(spec.GENESIS_EPOCH)
    assert int(spec.compute_epoch_at_slot(
        spec.get_current_slot(store))) == int(spec.GENESIS_EPOCH) + 1
    _run_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_past_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + 2 * int(spec.config.SECONDS_PER_SLOT)
                 * int(spec.SLOTS_PER_EPOCH))
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    attestation = get_valid_attestation(spec, state, slot=state.slot,
                                        signed=True)
    assert int(attestation.data.target.epoch) == int(spec.GENESIS_EPOCH)
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_mismatched_target_and_slot(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT)
                 * int(spec.SLOTS_PER_EPOCH))
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    attestation = get_valid_attestation(spec, state, slot=block.slot)
    attestation.data.target.epoch += 1
    sign_attestation(spec, state, attestation)
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_inconsistent_target_and_head(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + 2 * int(spec.config.SECONDS_PER_SLOT)
                 * int(spec.SLOTS_PER_EPOCH))

    # chain 1: empty through epoch 1
    target_state_1 = state.copy()
    next_epoch(spec, target_state_1)

    # chain 2: diverges with a different first block
    target_state_2 = state.copy()
    diff_block = build_empty_block_for_next_slot(spec, target_state_2)
    signed_diff_block = state_transition_and_sign_block(
        spec, target_state_2, diff_block)
    spec.on_block(store, signed_diff_block)
    next_epoch(spec, target_state_2)
    next_slot(spec, target_state_2)

    head_block = build_empty_block_for_next_slot(spec, target_state_1)
    signed_head_block = state_transition_and_sign_block(
        spec, target_state_1, head_block)
    spec.on_block(store, signed_head_block)

    # attest chain 1's head but claim chain 2's target
    attestation = get_valid_attestation(spec, target_state_1,
                                        slot=head_block.slot,
                                        signed=False)
    epoch = spec.compute_epoch_at_slot(attestation.data.slot)
    attestation.data.target = spec.Checkpoint(
        epoch=epoch, root=spec.get_block_root(target_state_2, epoch))
    sign_attestation(spec, state, attestation)
    assert spec.get_block_root(target_state_1, epoch) \
        != attestation.data.target.root
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_target_block_not_in_store(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT)
                 * (int(spec.SLOTS_PER_EPOCH) + 1))
    target_epoch = spec.get_current_epoch(state) + 1
    transition_to(spec, state,
                  spec.compute_start_slot_at_epoch(target_epoch) - 1)
    target_block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, target_block)
    # target block NOT added to the store
    attestation = get_valid_attestation(spec, state,
                                        slot=target_block.slot,
                                        signed=True)
    assert attestation.data.target.root == hash_tree_root(target_block)
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_target_checkpoint_not_in_store(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT)
                 * (int(spec.SLOTS_PER_EPOCH) + 1))
    target_epoch = spec.get_current_epoch(state) + 1
    transition_to(spec, state,
                  spec.compute_start_slot_at_epoch(target_epoch) - 1)
    target_block = build_empty_block_for_next_slot(spec, state)
    signed_target_block = state_transition_and_sign_block(
        spec, state, target_block)
    spec.on_block(store, signed_target_block)
    # checkpoint state derives on demand
    attestation = get_valid_attestation(spec, state,
                                        slot=target_block.slot,
                                        signed=True)
    assert attestation.data.target.root == hash_tree_root(target_block)
    _run_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_target_checkpoint_not_in_store_diff_slot(
        spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT)
                 * (int(spec.SLOTS_PER_EPOCH) + 1))
    target_epoch = spec.get_current_epoch(state) + 1
    transition_to(spec, state,
                  spec.compute_start_slot_at_epoch(target_epoch) - 2)
    target_block = build_empty_block_for_next_slot(spec, state)
    signed_target_block = state_transition_and_sign_block(
        spec, state, target_block)
    spec.on_block(store, signed_target_block)
    # attest one empty slot later: target root crosses the skip
    attestation_slot = target_block.slot + 1
    transition_to(spec, state, attestation_slot)
    attestation = get_valid_attestation(spec, state,
                                        slot=attestation_slot,
                                        signed=True)
    assert attestation.data.target.root == hash_tree_root(target_block)
    _run_on_attestation(spec, state, store, attestation)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_beacon_block_not_in_store(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT)
                 * (int(spec.SLOTS_PER_EPOCH) + 1))
    target_epoch = spec.get_current_epoch(state) + 1
    transition_to(spec, state,
                  spec.compute_start_slot_at_epoch(target_epoch) - 1)
    target_block = build_empty_block_for_next_slot(spec, state)
    signed_target_block = state_transition_and_sign_block(
        spec, state, target_block)
    spec.on_block(store, signed_target_block)
    head_block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, head_block)
    # head block NOT added to the store
    attestation = get_valid_attestation(spec, state,
                                        slot=head_block.slot,
                                        signed=True)
    assert attestation.data.target.root == hash_tree_root(target_block)
    assert attestation.data.beacon_block_root \
        == hash_tree_root(head_block)
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_future_epoch(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + 3 * int(spec.config.SECONDS_PER_SLOT))
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    # state advances an epoch; the store does not
    next_epoch(spec, state)
    attestation = get_valid_attestation(spec, state, slot=state.slot,
                                        signed=True)
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_future_block(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT) * 5)
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    # LMD vote for a block NEWER than the attestation slot
    attestation = get_valid_attestation(spec, state,
                                        slot=block.slot - 1,
                                        signed=False)
    attestation.data.beacon_block_root = hash_tree_root(block)
    sign_attestation(spec, state, attestation)
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_same_slot(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + int(spec.config.SECONDS_PER_SLOT))
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    # attestation for the current slot arrives a slot too early
    attestation = get_valid_attestation(spec, state, slot=block.slot,
                                        signed=True)
    _run_on_attestation(spec, state, store, attestation, valid=False)


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_on_attestation_invalid_attestation(spec, state):
    store = get_genesis_forkchoice_store(spec, state)
    spec.on_tick(store,
                 int(store.time) + 3 * int(spec.config.SECONDS_PER_SLOT))
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed_block)
    attestation = get_valid_attestation(spec, state, slot=block.slot,
                                        signed=True)
    # corrupt the committee reference
    if spec.is_post("electra"):
        attestation.committee_bits = type(attestation.committee_bits)()
    else:
        attestation.data.index = \
            spec.MAX_COMMITTEES_PER_SLOT * spec.SLOTS_PER_EPOCH
    _run_on_attestation(spec, state, store, attestation, valid=False)
