"""Fulu sampling security/bandwidth invariants (reference
test/fulu/unittests/test_security.py, 1 def — mainnet numbers)."""
from ...test_infra.context import (
    spec_test, no_vectors, with_all_phases_from, with_presets)


@with_all_phases_from("fulu")
@with_presets(["mainnet"],
              reason="security/bandwidth budgets are mainnet numbers")
@spec_test
@no_vectors
def test_sampling_config(spec):
    probability_of_unavailable = 2 ** (
        -int(spec.config.SAMPLES_PER_SLOT))
    assert probability_of_unavailable <= 0.01
    column_size_in_bytes = (int(spec.FIELD_ELEMENTS_PER_CELL)
                            * int(spec.BYTES_PER_FIELD_ELEMENT)
                            * int(spec.config.MAX_BLOBS_PER_BLOCK))
    bytes_per_slot = column_size_in_bytes \
        * int(spec.config.SAMPLES_PER_SLOT)
    assert bytes_per_slot // int(spec.config.SECONDS_PER_SLOT) < 10000
