"""Remaining per-fork unit batteries in one module:

- altair config-override units (reference
  test/altair/unittests/test_config_override.py, 3 defs)
- altair sync-subnet pubkeys (test/altair/unittests/networking/
  test_networking.py, 2 defs)
- deneb blob-sidecar inclusion proofs (test/deneb/unittests/validator/
  test_validator.py, 3 defs)
"""
import random

import pytest

from ...ssz import hash_tree_root, uint64
from ...test_infra.context import (
    spec_state_test, no_vectors, with_all_phases, with_all_phases_from,
    with_phases, with_config_overrides, never_bls)
from ...test_infra.blob import get_sample_blob_tx
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, sign_block, transition_to)

# --- config override ------------------------------------------------------


@with_phases(["altair"])
@with_config_overrides({"GENESIS_FORK_VERSION": "0x12345678",
                        "ALTAIR_FORK_VERSION": "0x11111111",
                        "ALTAIR_FORK_EPOCH": 4})
@spec_state_test
@no_vectors
@never_bls
def test_config_override(spec, state):
    assert int(spec.config.ALTAIR_FORK_EPOCH) == 4
    assert spec.config.GENESIS_FORK_VERSION != "0x00000000"
    assert spec.config.GENESIS_FORK_VERSION == "0x12345678"
    assert spec.config.ALTAIR_FORK_VERSION == "0x11111111"
    assert bytes(state.fork.current_version) == bytes.fromhex("11111111")


@with_all_phases
@spec_state_test
@no_vectors
@never_bls
def test_config_override_matching_fork_epochs(spec, state):
    """The genesis state's fork version is its own fork's configured
    version, and the config's fork-epoch schedule is monotonic
    (the reference asserts this under a zeroed-epoch config; our
    harness builds states at the fork directly, so the state-side
    check binds version, not epoch)."""
    version_fields = {"phase0": "GENESIS_FORK_VERSION"}
    for f in ("altair", "bellatrix", "capella", "deneb", "electra",
              "fulu"):
        version_fields[f] = f"{f.upper()}_FORK_VERSION"
    field = version_fields.get(spec.fork)
    if field is not None and hasattr(spec.config, field):
        assert bytes(state.fork.current_version) == bytes.fromhex(
            str(getattr(spec.config, field))[2:])
    # schedule monotonicity where epochs are configured
    prev = 0
    for f in ("ALTAIR", "BELLATRIX", "CAPELLA", "DENEB", "ELECTRA"):
        epoch_field = f"{f}_FORK_EPOCH"
        if hasattr(spec.config, epoch_field):
            cur = int(getattr(spec.config, epoch_field))
            assert cur >= prev
            prev = cur


@with_phases(["altair"])
@with_config_overrides({"ALTAIR_FORK_VERSION": "0x11111111"})
@spec_state_test
@no_vectors
@never_bls
def test_config_override_isolation(spec, state):
    """Overrides live on a per-test spec instance; the cached default
    target is untouched (the reference's across-phases isolation
    property)."""
    from ...specs import get_spec
    assert spec.config.ALTAIR_FORK_VERSION == "0x11111111"
    default_spec = get_spec("altair", "minimal")
    assert default_spec.config.ALTAIR_FORK_VERSION != "0x11111111"


# --- altair networking ----------------------------------------------------


def _check_subcommittee_pubkeys(spec, state, committee):
    size = int(spec.SYNC_COMMITTEE_SIZE) \
        // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    subcommittee_index = 1
    i = subcommittee_index * size
    expect = [bytes(k) for k in committee.pubkeys[i:i + size]]
    got = [bytes(k) for k in spec.get_sync_subcommittee_pubkeys(
        state, subcommittee_index)]
    assert got == expect


@with_all_phases_from("altair")
@spec_state_test
@no_vectors
@never_bls
def test_get_sync_subcommittee_pubkeys_current_sync_committee(spec, state):
    transition_to(spec, state,
                  uint64(int(spec.SLOTS_PER_EPOCH)
                         * int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)))
    next_slot_epoch = spec.compute_epoch_at_slot(
        uint64(int(state.slot) + 1))
    assert spec.compute_sync_committee_period(
        spec.get_current_epoch(state)) \
        == spec.compute_sync_committee_period(next_slot_epoch)
    _check_subcommittee_pubkeys(spec, state,
                                state.current_sync_committee)


@with_all_phases_from("altair")
@spec_state_test
@no_vectors
@never_bls
def test_get_sync_subcommittee_pubkeys_next_sync_committee(spec, state):
    transition_to(spec, state,
                  uint64(int(spec.SLOTS_PER_EPOCH)
                         * int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
                         - 1))
    next_slot_epoch = spec.compute_epoch_at_slot(
        uint64(int(state.slot) + 1))
    assert spec.compute_sync_committee_period(
        spec.get_current_epoch(state)) \
        != spec.compute_sync_committee_period(next_slot_epoch)
    _check_subcommittee_pubkeys(spec, state, state.next_sync_committee)


# --- deneb blob sidecar inclusion proofs ----------------------------------


def _sample_sidecars(spec, state, rng):
    block = build_empty_block_for_next_slot(spec, state)
    # one blob per tx: the inclusion-proof structure under test is
    # independent of blob count and the pure-Python KZG is ~4s/blob
    opaque_tx_1, blobs_1, commitments_1, proofs_1 = get_sample_blob_tx(
        spec, blob_count=1, rng=rng)
    opaque_tx_2, blobs_2, commitments_2, proofs_2 = get_sample_blob_tx(
        spec, blob_count=1, rng=rng)
    assert opaque_tx_1 != opaque_tx_2
    block.body.blob_kzg_commitments = commitments_1 + commitments_2
    block.body.execution_payload.transactions = [opaque_tx_1, opaque_tx_2]
    signed_block = sign_block(spec, state, block)
    return spec.get_blob_sidecars(signed_block, blobs_1 + blobs_2,
                                  proofs_1 + proofs_2)


@pytest.mark.slow  # full-body merkle proof build (~10 s each)
@with_all_phases_from("deneb", to="electra")
@spec_state_test
@no_vectors
@never_bls
def test_blob_sidecar_inclusion_proof_correct(spec, state):
    rng = random.Random(1234)
    for sidecar in _sample_sidecars(spec, state, rng):
        assert spec.verify_blob_sidecar_inclusion_proof(sidecar)


@pytest.mark.slow  # full-body merkle proof build (~10 s each)
@with_all_phases_from("deneb", to="electra")
@spec_state_test
@no_vectors
@never_bls
def test_blob_sidecar_inclusion_proof_incorrect_wrong_body(spec, state):
    rng = random.Random(1234)
    for sidecar in _sample_sidecars(spec, state, rng):
        header = sidecar.signed_block_header.message
        header.body_root = spec.hash(bytes(header.body_root))
        assert not spec.verify_blob_sidecar_inclusion_proof(sidecar)


@pytest.mark.slow  # full-body merkle proof build (~10 s each)
@with_all_phases_from("deneb", to="electra")
@spec_state_test
@no_vectors
@never_bls
def test_blob_sidecar_inclusion_proof_incorrect_wrong_proof(spec, state):
    rng = random.Random(1234)
    for sidecar in _sample_sidecars(spec, state, rng):
        sidecar.kzg_commitment_inclusion_proof = [
            b"\x00" * 32] * int(spec.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH)
        assert not spec.verify_blob_sidecar_inclusion_proof(sidecar)
