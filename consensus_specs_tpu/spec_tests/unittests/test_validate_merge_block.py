"""validate_merge_block unit battery (reference
test/bellatrix/unittests/test_validate_merge_block.py, 8 defs): the
terminal PoW block rule and the TERMINAL_BLOCK_HASH override path,
called directly (no store)."""
from random import Random

from ...ssz import uint256
from ...test_infra.context import (
    spec_state_test, no_vectors, with_all_phases_from,
    with_config_overrides, never_bls)
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, build_empty_execution_payload)
from ...test_infra.pow_block import (
    prepare_random_pow_chain, pow_chain_patch,
    recompute_payload_block_hash)

TBH = "0x" + "00" * 31 + "01"


def _merge_block(spec, state, parent_hash):
    block = build_empty_block_for_next_slot(spec, state)
    lookahead = state.copy()
    spec.process_slots(lookahead, block.slot)
    payload = build_empty_execution_payload(spec, lookahead)
    payload.parent_hash = parent_hash
    recompute_payload_block_hash(spec, payload)
    block.body.execution_payload = payload
    return block


def _run_validate_merge_block(spec, pow_chain, block, valid=True):
    with pow_chain_patch(spec, list(pow_chain)):
        caught = False
        try:
            spec.validate_merge_block(block)
        except AssertionError:
            caught = True
    assert caught != valid


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_success(spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 2, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    pow_chain.head(-1).total_difficulty = uint256(ttd - 1)
    pow_chain.head().total_difficulty = uint256(ttd)
    block = _merge_block(spec, state, pow_chain.head().block_hash)
    _run_validate_merge_block(spec, pow_chain, block)


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_fail_block_lookup(spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 2, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    pow_chain.head(-1).total_difficulty = uint256(ttd - 1)
    pow_chain.head().total_difficulty = uint256(ttd)
    # payload parent is NOT in the chain view (default zero hash)
    block = build_empty_block_for_next_slot(spec, state)
    _run_validate_merge_block(spec, pow_chain, block, valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_fail_parent_block_lookup(spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 1, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    pow_chain.head().total_difficulty = uint256(ttd)
    block = _merge_block(spec, state, pow_chain.head().block_hash)
    _run_validate_merge_block(spec, pow_chain, block, valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_fail_after_terminal(spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 2, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    pow_chain.head(-1).total_difficulty = uint256(ttd)
    pow_chain.head().total_difficulty = uint256(ttd + 1)
    block = _merge_block(spec, state, pow_chain.head().block_hash)
    _run_validate_merge_block(spec, pow_chain, block, valid=False)


@with_all_phases_from("bellatrix")
@with_config_overrides({"TERMINAL_BLOCK_HASH": TBH,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 0})
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_tbh_override_success(spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 2, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    # TTD NOT reached: only the TBH override can admit the block
    pow_chain.head(-1).total_difficulty = uint256(ttd - 2)
    pow_chain.head().total_difficulty = uint256(ttd - 1)
    pow_chain.head().block_hash = bytes.fromhex(TBH[2:])
    block = _merge_block(spec, state, pow_chain.head().block_hash)
    _run_validate_merge_block(spec, pow_chain, block)


@with_all_phases_from("bellatrix")
@with_config_overrides({"TERMINAL_BLOCK_HASH": TBH,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 0})
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_fail_parent_hash_is_not_tbh(spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 2, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    # TTD reached — irrelevant once TBH is configured
    pow_chain.head(-1).total_difficulty = uint256(ttd - 1)
    pow_chain.head().total_difficulty = uint256(ttd)
    block = _merge_block(spec, state, pow_chain.head().block_hash)
    _run_validate_merge_block(spec, pow_chain, block, valid=False)


@with_all_phases_from("bellatrix")
@with_config_overrides({"TERMINAL_BLOCK_HASH": TBH,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 1})
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_terminal_block_hash_fail_activation_not_reached(
        spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 2, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    pow_chain.head(-1).total_difficulty = uint256(ttd - 1)
    pow_chain.head().total_difficulty = uint256(ttd)
    pow_chain.head().block_hash = bytes.fromhex(TBH[2:])
    block = _merge_block(spec, state, pow_chain.head().block_hash)
    # genesis epoch < activation epoch: reject even with TBH parent
    _run_validate_merge_block(spec, pow_chain, block, valid=False)


@with_all_phases_from("bellatrix")
@with_config_overrides({"TERMINAL_BLOCK_HASH": TBH,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 1})
@spec_state_test
@no_vectors
@never_bls
def test_validate_merge_block_fail_activation_not_reached_parent_hash_is_not_tbh(
        spec, state):
    rng = Random(3131)
    pow_chain = prepare_random_pow_chain(spec, 2, rng)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    pow_chain.head(-1).total_difficulty = uint256(ttd - 1)
    pow_chain.head().total_difficulty = uint256(ttd)
    block = _merge_block(spec, state, pow_chain.head().block_hash)
    _run_validate_merge_block(spec, pow_chain, block, valid=False)
