"""EIP-7685 execution-requests (de)serialization units (reference
test/electra/unittests/test_execution_requests.py, 8 defs)."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_test, no_vectors, with_all_phases_from)


def _roundtrip(spec, execution_requests):
    serialized = spec.get_execution_requests_list(execution_requests)
    deserialized = spec.get_execution_requests(serialized)
    assert deserialized == execution_requests


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_serialization_round_trip__empty(spec):
    _roundtrip(spec, spec.ExecutionRequests())


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_serialization_round_trip__one_request(spec):
    _roundtrip(spec, spec.ExecutionRequests(
        deposits=[spec.DepositRequest()]))


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_serialization_round_trip__multiple_requests(spec):
    _roundtrip(spec, spec.ExecutionRequests(
        deposits=[spec.DepositRequest()],
        withdrawals=[spec.WithdrawalRequest()],
        consolidations=[spec.ConsolidationRequest()]))


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_serialization_round_trip__one_request_with_real_data(
        spec):
    _roundtrip(spec, spec.ExecutionRequests(
        deposits=[spec.DepositRequest(
            pubkey=b"\xaa" * 48,
            withdrawal_credentials=b"\xbb" * 32,
            amount=uint64(11111111),
            signature=b"\xcc" * 96,
            index=uint64(22222222))]))


def _expect_reject(spec, serialized_requests):
    try:
        spec.get_execution_requests(serialized_requests)
        raise RuntimeError("malformed request list accepted")
    except (AssertionError, ValueError):
        pass


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_deserialize__reject_duplicate_request(spec):
    serialized_withdrawal = 76 * b"\x0a"
    _expect_reject(spec, [
        spec.WITHDRAWAL_REQUEST_TYPE + serialized_withdrawal,
        spec.WITHDRAWAL_REQUEST_TYPE + serialized_withdrawal])


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_deserialize__reject_out_of_order_requests(spec):
    requests = [spec.WITHDRAWAL_REQUEST_TYPE + 76 * b"\x0a",
                spec.DEPOSIT_REQUEST_TYPE + 192 * b"\x0b"]
    assert requests[0][0] > requests[1][0]
    _expect_reject(spec, requests)


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_deserialize__reject_empty_request(spec):
    _expect_reject(spec, [b"\x01"])


@with_all_phases_from("electra")
@spec_test
@no_vectors
def test_requests_deserialize__reject_unexpected_request_type(spec):
    _expect_reject(spec, [b"\x03\xff\xff\xff"])
