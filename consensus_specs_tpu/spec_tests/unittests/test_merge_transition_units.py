"""Merge-transition predicate units (reference
test/bellatrix/unittests/test_is_valid_terminal_pow_block.py, 3 defs +
test_transition.py, 3 defs)."""
from random import Random

from ...ssz import uint256
from ...test_infra.context import (
    spec_state_test, no_vectors, with_all_phases_from, never_bls)
from ...test_infra.blocks import build_empty_execution_payload
from ...test_infra.pow_block import (
    prepare_random_pow_block, build_state_with_complete_transition,
    build_state_with_incomplete_transition)


# --- is_valid_terminal_pow_block ------------------------------------------

@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_is_valid_terminal_pow_block_success_valid(spec, state):
    rng = Random(3131)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    parent_block = prepare_random_pow_block(spec, rng)
    parent_block.total_difficulty = uint256(ttd - 1)
    block = prepare_random_pow_block(spec, rng)
    block.parent_hash = parent_block.block_hash
    block.total_difficulty = uint256(ttd)
    assert spec.is_valid_terminal_pow_block(block, parent_block)


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_is_valid_terminal_pow_block_fail_before_terminal(spec, state):
    rng = Random(3131)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    parent_block = prepare_random_pow_block(spec, rng)
    parent_block.total_difficulty = uint256(ttd - 2)
    block = prepare_random_pow_block(spec, rng)
    block.parent_hash = parent_block.block_hash
    block.total_difficulty = uint256(ttd - 1)
    assert not spec.is_valid_terminal_pow_block(block, parent_block)


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_is_valid_terminal_pow_block_fail_just_after_terminal(spec, state):
    rng = Random(3131)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    parent_block = prepare_random_pow_block(spec, rng)
    parent_block.total_difficulty = uint256(ttd)
    block = prepare_random_pow_block(spec, rng)
    block.parent_hash = parent_block.block_hash
    block.total_difficulty = uint256(ttd + 1)
    assert not spec.is_valid_terminal_pow_block(block, parent_block)


# --- is_merge_transition_complete / _block / is_execution_enabled ---------

@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_fail_merge_complete(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    assert not spec.is_merge_transition_complete(state)


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_success_merge_complete(spec, state):
    state = build_state_with_complete_transition(spec, state)
    assert spec.is_merge_transition_complete(state)


# (complete_transition, with_payload) -> (is_merge_block, exec_enabled)
EXPECTED = [
    (True, True, False, True),
    (True, False, False, True),
    (False, True, True, True),
    (False, False, False, False),
]


@with_all_phases_from("bellatrix")
@spec_state_test
@no_vectors
@never_bls
def test_is_merge_block_and_is_execution_enabled(spec, state):
    for (complete, with_payload, is_merge_block, enabled) in EXPECTED:
        if complete:
            case_state = build_state_with_complete_transition(spec, state)
        else:
            case_state = build_state_with_incomplete_transition(spec,
                                                                state)
        body = spec.BeaconBlockBody()
        if with_payload:
            body.execution_payload = build_empty_execution_payload(
                spec, case_state)
        assert spec.is_merge_transition_block(case_state, body) \
            == is_merge_block
        assert spec.is_execution_enabled(case_state, body) == enabled
