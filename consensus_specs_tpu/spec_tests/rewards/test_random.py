"""Randomized reward-delta states (reference:
test/phase0/rewards/test_random.py shape; vector format
tests/formats/rewards).  Seeded scrambles of participation, balances,
and registry status, emitted through the shared per-component deltas
path so the scalar and vectorized engines stay pinned together.
"""
import random as _random

from ...ssz import uint64
from ...test_infra.context import (
    default_activation_threshold, low_balances, misc_balances, never_bls,
    spec_state_test, with_all_phases, with_custom_state,
    zero_activation_threshold)
from ...test_infra.blocks import next_epoch, transition_to
from ...test_infra.attestations import next_epoch_with_attestations
from .test_basic import _emit_deltas, _full_flags


def _randomize_deltas_state(spec, state, rng, *, leak=False,
                            exits=False):
    """Scramble participation + registry the way the reference's
    run_deltas randomization does: random flags/bits, random inactivity
    scores, optional exits, optional active leak."""
    if leak:
        target = (int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3) * \
            int(spec.SLOTS_PER_EPOCH)
        transition_to(spec, state, uint64(target))
        assert spec.is_in_inactivity_leak(state)
    else:
        next_epoch(spec, state)
        assert not spec.is_in_inactivity_leak(state)

    n = len(state.validators)
    if spec.is_post("altair"):
        hi = _full_flags(spec) + 1
        state.previous_epoch_participation = [
            rng.randrange(0, hi) for _ in range(n)]
        bias = int(spec.config.INACTIVITY_SCORE_BIAS)
        state.inactivity_scores = [
            rng.randrange(0, 8 * bias) for _ in range(n)]
    else:
        if not leak:
            next_epoch_with_attestations(spec, state, False, True)
        for att in state.previous_epoch_attestations:
            bits = att.aggregation_bits
            for j in range(len(bits)):
                if rng.random() < 0.4:
                    bits[j] = False
            att.inclusion_delay = uint64(
                rng.randrange(1, int(spec.SLOTS_PER_EPOCH) + 1))

    if exits:
        epoch = int(spec.get_current_epoch(state))
        for i in rng.sample(range(n), max(n // 8, 1)):
            state.validators[i].exit_epoch = uint64(max(epoch, 1))
            state.validators[i].withdrawable_epoch = uint64(epoch + 10)


def _run_random(spec, state, tag, **kw):
    rng = _random.Random(f"{spec.fork}:{spec.preset_name}:{tag}")
    _randomize_deltas_state(spec, state, rng, **kw)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_full_random_0(spec, state):
    yield from _run_random(spec, state, "r0", leak=True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_random_1(spec, state):
    yield from _run_random(spec, state, "r1", leak=True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_random_2(spec, state):
    yield from _run_random(spec, state, "r2", leak=True, exits=True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_random_3(spec, state):
    yield from _run_random(spec, state, "r3", leak=True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_random_4(spec, state):
    yield from _run_random(spec, state, "r4", leak=True, exits=True)


@with_all_phases
@with_custom_state(balances_fn=low_balances,
                   threshold_fn=zero_activation_threshold)
@spec_state_test
@never_bls
def test_full_random_low_balances_0(spec, state):
    yield from _run_random(spec, state, "lb0", leak=True)


@with_all_phases
@with_custom_state(balances_fn=low_balances,
                   threshold_fn=zero_activation_threshold)
@spec_state_test
@never_bls
def test_full_random_low_balances_1(spec, state):
    yield from _run_random(spec, state, "lb1", leak=True, exits=True)


@with_all_phases
@with_custom_state(balances_fn=misc_balances,
                   threshold_fn=default_activation_threshold)
@spec_state_test
@never_bls
def test_full_random_misc_balances(spec, state):
    yield from _run_random(spec, state, "misc", leak=True, exits=True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_random_without_leak_0(spec, state):
    yield from _run_random(spec, state, "nl0", leak=False)


@with_all_phases
@spec_state_test
@never_bls
def test_full_random_without_leak_and_current_exit_0(spec, state):
    yield from _run_random(spec, state, "nlx0", leak=False, exits=True)
