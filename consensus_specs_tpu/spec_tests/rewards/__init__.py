"""Per-component reward/penalty delta spec tests."""

REWARDS_HANDLERS = {
    "basic": "consensus_specs_tpu.spec_tests.rewards.test_basic",
    "leak": "consensus_specs_tpu.spec_tests.rewards.test_leak",
    "random": "consensus_specs_tpu.spec_tests.rewards.test_random",
}
