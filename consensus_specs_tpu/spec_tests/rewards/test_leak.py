"""Reward deltas under an inactivity leak (reference:
test/phase0/rewards/test_leak.py shape; vector format
tests/formats/rewards)."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases, with_all_phases_from, never_bls)
from ...test_infra.blocks import transition_to
from .test_basic import Deltas, _emit_deltas


def _enter_leak(spec, state, participating: bool):
    """Advance past MIN_EPOCHS_TO_INACTIVITY_PENALTY without finality;
    optionally leave everyone participating."""
    target = (int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3) * \
        int(spec.SLOTS_PER_EPOCH)
    transition_to(spec, state, uint64(target))
    n = len(state.validators)
    if spec.is_post("altair"):
        flags = 0
        if participating:
            for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
                flags = spec.add_flag(flags, i)
        state.previous_epoch_participation = [flags] * n
        state.inactivity_scores = [
            0 if participating
            else int(spec.config.INACTIVITY_SCORE_BIAS) * 4] * n
    assert spec.is_in_inactivity_leak(state)


@with_all_phases
@spec_state_test
@never_bls
def test_leak_empty_participation(spec, state):
    """Leaking with no participation: inactivity penalties bite."""
    _enter_leak(spec, state, participating=False)
    yield "pre", state.copy()
    deltas = list(_emit_deltas(spec, state))
    for name, d in deltas:
        yield name, d
    _, inactivity = deltas[-1]
    assert sum(int(p) for p in inactivity.penalties) > 0
    assert sum(int(r) for r in inactivity.rewards) == 0


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_full_participation(spec, state):
    """Leaking but fully participating: no inactivity penalties (zero
    scores).  altair+ only — phase0 participation lives in pending
    attestations, which _enter_leak's empty-slot advance cannot
    populate, so a phase0 case here would mislabel zero participation
    as full."""
    _enter_leak(spec, state, participating=True)
    yield "pre", state.copy()
    deltas = list(_emit_deltas(spec, state))
    for name, d in deltas:
        yield name, d
    if spec.is_post("altair"):
        _, inactivity = deltas[-1]
        assert sum(int(p) for p in inactivity.penalties) == 0


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_half_participation_mixed_scores(spec, state):
    """Half the registry leaks with climbing inactivity scores while
    the other half participates with zeroed scores: penalties land
    only on the idle half."""
    _enter_leak(spec, state, participating=False)
    n = len(state.validators)
    flags = 0
    for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        flags = spec.add_flag(flags, i)
    state.previous_epoch_participation = [
        flags if i % 2 == 0 else 0 for i in range(n)]
    state.inactivity_scores = [
        0 if i % 2 == 0
        else int(spec.config.INACTIVITY_SCORE_BIAS) * 8
        for i in range(n)]
    yield "pre", state.copy()
    deltas = list(_emit_deltas(spec, state))
    for name, d in deltas:
        yield name, d
    _, inactivity = deltas[-1]
    for i in range(n):
        if i % 2 == 0:
            assert int(inactivity.penalties[i]) == 0
        else:
            assert int(inactivity.penalties[i]) > 0


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_slashed_validators_still_penalized(spec, state):
    """Slashed validators cannot earn target credit, so the leak's
    inactivity penalty reaches them even if their flags are set."""
    _enter_leak(spec, state, participating=True)
    n = len(state.validators)
    epoch = int(spec.get_current_epoch(state))
    scores = list(state.inactivity_scores)
    for i in range(0, n, 4):
        state.validators[i].slashed = True
        state.validators[i].withdrawable_epoch = uint64(
            epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR))
        scores[i] = int(spec.config.INACTIVITY_SCORE_BIAS) * 4
    state.inactivity_scores = scores
    yield "pre", state.copy()
    deltas = list(_emit_deltas(spec, state))
    for name, d in deltas:
        yield name, d
    _, inactivity = deltas[-1]
    for i in range(0, n, 4):
        assert int(inactivity.penalties[i]) > 0


from .test_basic import (  # noqa: E402
    _emit_deltas as _deltas, _full_flags, _set_participation_fraction)


def _emit_all(spec, state):
    yield "pre", state.copy()
    for name, d in _deltas(spec, state):
        yield name, d


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_quarter_participation(spec, state):
    _enter_leak(spec, state, participating=False)
    n = len(state.validators)
    full = _full_flags(spec)
    state.previous_epoch_participation = [
        full if i % 4 == 0 else 0 for i in range(n)]
    state.inactivity_scores = [
        0 if i % 4 == 0 else int(spec.config.INACTIVITY_SCORE_BIAS) * 4
        for i in range(n)]
    yield from _emit_all(spec, state)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_correct_target_incorrect_head(spec, state):
    """Under a leak, target credit still cancels inactivity penalties
    while head rewards are zeroed (leak scaling)."""
    _enter_leak(spec, state, participating=False)
    n = len(state.validators)
    partial = spec.add_flag(
        spec.add_flag(0, int(spec.TIMELY_SOURCE_FLAG_INDEX)),
        int(spec.TIMELY_TARGET_FLAG_INDEX))
    state.previous_epoch_participation = [partial] * n
    state.inactivity_scores = [0] * n
    yield from _emit_all(spec, state)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_with_exited_validators(spec, state):
    _enter_leak(spec, state, participating=True)
    epoch = int(spec.get_current_epoch(state))
    for i in range(0, len(state.validators), 5):
        state.validators[i].exit_epoch = uint64(max(epoch - 1, 1))
        state.validators[i].withdrawable_epoch = uint64(epoch + 10)
    yield from _emit_all(spec, state)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_with_not_yet_activated_validators(spec, state):
    _enter_leak(spec, state, participating=True)
    epoch = int(spec.get_current_epoch(state))
    for i in range(0, len(state.validators), 5):
        state.validators[i].activation_epoch = uint64(epoch + 4)
    yield from _emit_all(spec, state)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_low_effective_balance(spec, state):
    _enter_leak(spec, state, participating=False)
    floor = uint64(int(spec.config.EJECTION_BALANCE))
    for i in range(0, len(state.validators), 3):
        state.validators[i].effective_balance = floor
    yield from _emit_all(spec, state)


def _deep_leak(spec, state, epochs: int):
    """Leak that has been running `epochs` epochs: scores scaled to
    epochs * bias for the idle half."""
    _enter_leak(spec, state, participating=False)
    n = len(state.validators)
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    state.inactivity_scores = [epochs * bias] * n


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_seven_epochs(spec, state):
    _deep_leak(spec, state, 7)
    yield from _emit_all(spec, state)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_ten_epochs(spec, state):
    _deep_leak(spec, state, 10)
    yield from _emit_all(spec, state)


@with_all_phases_from("altair")
@spec_state_test
@never_bls
def test_leak_full_random_participation(spec, state):
    """Seeded random flag mix under an active leak."""
    import random as _r
    rng = _r.Random(f"{spec.fork}:leak-random")
    _enter_leak(spec, state, participating=False)
    n = len(state.validators)
    hi = _full_flags(spec) + 1
    state.previous_epoch_participation = [
        rng.randrange(0, hi) for _ in range(n)]
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    state.inactivity_scores = [
        rng.randrange(0, 10 * bias) for _ in range(n)]
    yield from _emit_all(spec, state)
