"""Reward/penalty component-delta tests (reference test/helpers/rewards.py
capability; vector format tests/formats/rewards: one Deltas object per
component).

phase0 emits source/target/head/inclusion_delay/inactivity components from
the pending-attestation path; altair+ emits the three flag components plus
inactivity from participation flags.
"""
from ...ssz import List, uint64
from ...ssz.types import Container
from ...test_infra.context import (
    spec_state_test, with_all_phases, never_bls)
from ...test_infra.blocks import next_epoch
from ...test_infra.attestations import next_epoch_with_attestations

VALIDATOR_REGISTRY_LIMIT = 2**40


class Deltas(Container):
    rewards: List[uint64, VALIDATOR_REGISTRY_LIMIT]
    penalties: List[uint64, VALIDATOR_REGISTRY_LIMIT]


def _emit_deltas(spec, state):
    """Yield per-component Deltas matching the scalar spec helpers."""
    from ...specs import epoch_fast
    with epoch_fast.scalar_epoch():
        if spec.is_post("altair"):
            names = ["source", "target", "head"]
            for flag_index, name in enumerate(names):
                rewards, penalties = spec.get_flag_index_deltas(
                    state, flag_index)
                yield f"{name}_deltas", Deltas(rewards=rewards,
                                               penalties=penalties)
            rewards, penalties = spec.get_inactivity_penalty_deltas(state)
            yield "inactivity_penalty_deltas", Deltas(
                rewards=rewards, penalties=penalties)
        else:
            pairs = [
                ("source_deltas", spec.get_source_deltas),
                ("target_deltas", spec.get_target_deltas),
                ("head_deltas", spec.get_head_deltas),
                ("inclusion_delay_deltas",
                 spec.get_inclusion_delay_deltas),
                ("inactivity_penalty_deltas",
                 spec.get_inactivity_penalty_deltas),
            ]
            for name, fn in pairs:
                rewards, penalties = fn(state)
                yield name, Deltas(rewards=rewards, penalties=penalties)


def _prepare_participation(spec, state, full=True):
    next_epoch(spec, state)
    if spec.is_post("altair"):
        n = len(state.validators)
        flags = 0
        if full:
            for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
                flags = spec.add_flag(flags, i)
        state.previous_epoch_participation = [flags] * n
    elif full:
        next_epoch_with_attestations(spec, state, False, True)


@with_all_phases
@spec_state_test
@never_bls
def test_full_participation(spec, state):
    _prepare_participation(spec, state, full=True)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_empty_participation(spec, state):
    _prepare_participation(spec, state, full=False)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_half_participation(spec, state):
    next_epoch(spec, state)
    if spec.is_post("altair"):
        n = len(state.validators)
        full = 0
        for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            full = spec.add_flag(full, i)
        state.previous_epoch_participation = [
            full if i % 2 == 0 else 0 for i in range(n)]
    else:
        next_epoch_with_attestations(spec, state, False, True)
        # halve the recorded aggregation bits
        for att in state.previous_epoch_attestations:
            bits = att.aggregation_bits
            for j in range(len(bits)):
                if j % 2:
                    bits[j] = False
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_quarter_participation(spec, state):
    next_epoch(spec, state)
    if spec.is_post("altair"):
        n = len(state.validators)
        full = 0
        for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            full = spec.add_flag(full, i)
        state.previous_epoch_participation = [
            full if i % 4 == 0 else 0 for i in range(n)]
    else:
        next_epoch_with_attestations(spec, state, False, True)
        for att in state.previous_epoch_attestations:
            bits = att.aggregation_bits
            for j in range(len(bits)):
                if j % 4:
                    bits[j] = False
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_correct_target_incorrect_head(spec, state):
    """Target credit without head credit: head rewards vanish while
    target/source rewards persist."""
    next_epoch(spec, state)
    if spec.is_post("altair"):
        n = len(state.validators)
        flags = spec.add_flag(
            spec.add_flag(0, int(spec.TIMELY_SOURCE_FLAG_INDEX)),
            int(spec.TIMELY_TARGET_FLAG_INDEX))
        state.previous_epoch_participation = [flags] * n
    else:
        next_epoch_with_attestations(spec, state, False, True)
        for att in state.previous_epoch_attestations:
            att.data.beacon_block_root = b"\x77" * 32   # wrong head
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_with_slashed_validators(spec, state):
    _prepare_participation(spec, state, full=True)
    epoch = int(spec.get_current_epoch(state))
    for i in range(0, len(state.validators), 4):
        state.validators[i].slashed = True
        state.validators[i].withdrawable_epoch = uint64(
            epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR))
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_with_exited_validators(spec, state):
    # mutate BEFORE building participation: exits change the active
    # set, hence committee shapes
    epoch = int(spec.get_current_epoch(state)) + 1
    for i in range(0, len(state.validators), 5):
        state.validators[i].exit_epoch = uint64(max(epoch - 1, 1))
        state.validators[i].withdrawable_epoch = uint64(epoch + 10)
    _prepare_participation(spec, state, full=True)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_with_not_yet_activated_validators(spec, state):
    # mutate BEFORE building participation (committee shapes)
    epoch = int(spec.get_current_epoch(state)) + 1
    for i in range(0, len(state.validators), 5):
        state.validators[i].activation_epoch = uint64(epoch + 4)
    _prepare_participation(spec, state, full=True)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_low_effective_balance_attesters(spec, state):
    """Validators at the ejection-balance floor still earn
    proportionally tiny rewards."""
    _prepare_participation(spec, state, full=True)
    for i in range(0, len(state.validators), 3):
        state.validators[i].effective_balance = uint64(
            int(spec.config.EJECTION_BALANCE))
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


def _full_flags(spec) -> int:
    flags = 0
    for i in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        flags = spec.add_flag(flags, i)
    return flags


def _set_participation_fraction(spec, state, keep_fn):
    """Thin participation to the validators selected by keep_fn(i)."""
    if spec.is_post("altair"):
        n = len(state.validators)
        full = _full_flags(spec)
        state.previous_epoch_participation = [
            full if keep_fn(i) else 0 for i in range(n)]
    else:
        for att in state.previous_epoch_attestations:
            bits = att.aggregation_bits
            for j in range(len(bits)):
                if not keep_fn(j):
                    bits[j] = False


@with_all_phases
@spec_state_test
@never_bls
def test_one_attestation_one_correct(spec, state):
    """A single participant: everyone else accrues penalties, the one
    attester earns every component."""
    _prepare_participation(spec, state, full=True)
    if spec.is_post("altair"):
        n = len(state.validators)
        flags = _full_flags(spec)
        state.previous_epoch_participation = [
            flags if i == 0 else 0 for i in range(n)]
    else:
        # keep only the first attestation, with a single bit set
        atts = list(state.previous_epoch_attestations)[:1]
        for att in atts:
            bits = att.aggregation_bits
            for j in range(1, len(bits)):
                bits[j] = False
        state.previous_epoch_attestations = atts
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_full_but_partial_participation(spec, state):
    """Every committee is covered but only ~2/3 of each participates."""
    _prepare_participation(spec, state, full=True)
    _set_participation_fraction(spec, state, lambda i: i % 3 != 0)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_low_effective_balance_did_not_attest(spec, state):
    """Ejection-floor validators that sat out: penalties stay
    proportional to their tiny effective balance."""
    _prepare_participation(spec, state, full=True)
    floor = uint64(int(spec.config.EJECTION_BALANCE))
    for i in range(0, len(state.validators), 3):
        state.validators[i].effective_balance = floor
    _set_participation_fraction(spec, state, lambda i: i % 3 != 0)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_full_half_correct_target_incorrect_head(spec, state):
    """Half the voters hit the target but miss the head."""
    next_epoch(spec, state)
    if spec.is_post("altair"):
        n = len(state.validators)
        full = _full_flags(spec)
        partial = spec.add_flag(
            spec.add_flag(0, int(spec.TIMELY_SOURCE_FLAG_INDEX)),
            int(spec.TIMELY_TARGET_FLAG_INDEX))
        state.previous_epoch_participation = [
            full if i % 2 else partial for i in range(n)]
    else:
        next_epoch_with_attestations(spec, state, False, True)
        for k, att in enumerate(state.previous_epoch_attestations):
            if k % 2 == 0:
                att.data.beacon_block_root = b"\x77" * 32
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_full_half_incorrect_target_correct_head(spec, state):
    """Half the voters miss the target (head credit requires target in
    altair's flag machinery; phase0 scores them independently)."""
    next_epoch(spec, state)
    if spec.is_post("altair"):
        n = len(state.validators)
        full = _full_flags(spec)
        partial = spec.add_flag(0, int(spec.TIMELY_SOURCE_FLAG_INDEX))
        state.previous_epoch_participation = [
            full if i % 2 else partial for i in range(n)]
    else:
        next_epoch_with_attestations(spec, state, False, True)
        for k, att in enumerate(state.previous_epoch_attestations):
            if k % 2 == 0:
                att.data.target.root = b"\x55" * 32
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_full_half_incorrect_target_incorrect_head(spec, state):
    """Half the voters carry source credit only."""
    next_epoch(spec, state)
    if spec.is_post("altair"):
        n = len(state.validators)
        full = _full_flags(spec)
        partial = spec.add_flag(0, int(spec.TIMELY_SOURCE_FLAG_INDEX))
        state.previous_epoch_participation = [
            full if i % 2 else partial for i in range(n)]
        state.inactivity_scores = [0] * n
    else:
        next_epoch_with_attestations(spec, state, False, True)
        for k, att in enumerate(state.previous_epoch_attestations):
            if k % 2 == 0:
                att.data.target.root = b"\x55" * 32
                att.data.beacon_block_root = b"\x77" * 32
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_all_phases
@spec_state_test
@never_bls
def test_all_balances_too_low_for_reward(spec, state):
    """Effective balances below one increment: base rewards collapse to
    the floor and deltas stay consistent."""
    _prepare_participation(spec, state, full=True)
    for v in state.validators:
        v.effective_balance = uint64(
            int(spec.EFFECTIVE_BALANCE_INCREMENT) // 2
            if int(spec.EFFECTIVE_BALANCE_INCREMENT) > 1 else 0)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


# ── phase0-only inclusion-delay component shapes (altair+ has no
#    inclusion-delay deltas; reference keeps these under phase0) ──────

from ...test_infra.context import with_phases  # noqa: E402


@with_phases(["phase0"])
@spec_state_test
@never_bls
def test_full_delay_one_slot(spec, state):
    _prepare_participation(spec, state, full=True)
    for att in state.previous_epoch_attestations:
        att.inclusion_delay = uint64(int(att.inclusion_delay) + 1)
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
@never_bls
def test_full_delay_max_slots(spec, state):
    _prepare_participation(spec, state, full=True)
    for att in state.previous_epoch_attestations:
        att.inclusion_delay = uint64(int(spec.SLOTS_PER_EPOCH))
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
@never_bls
def test_full_mixed_delay(spec, state):
    _prepare_participation(spec, state, full=True)
    for k, att in enumerate(state.previous_epoch_attestations):
        att.inclusion_delay = uint64(
            1 + (k % int(spec.SLOTS_PER_EPOCH)))
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
@never_bls
def test_proposer_not_in_attestations(spec, state):
    """Strip any attestation whose proposer also attested: the
    proposer-reward component of inclusion-delay deltas must skip
    them."""
    _prepare_participation(spec, state, full=True)
    kept = []
    for att in state.previous_epoch_attestations:
        bits = att.aggregation_bits
        committee = spec.get_beacon_committee(
            state, att.data.slot, att.data.index)
        proposer = int(att.proposer_index)
        filtered = [b and int(committee[j]) != proposer
                    for j, b in enumerate(bits)]
        if any(filtered):
            for j, b in enumerate(filtered):
                bits[j] = b
            kept.append(att)
    state.previous_epoch_attestations = kept
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)


@with_phases(["phase0"])
@spec_state_test
@never_bls
def test_duplicate_attestations_at_later_slots(spec, state):
    """Duplicate pending attestations with larger inclusion delays:
    the min-delay copy must win for the inclusion-delay component."""
    _prepare_participation(spec, state, full=True)
    dupes = []
    for att in list(state.previous_epoch_attestations)[:4]:
        d = att.copy()
        d.inclusion_delay = uint64(
            min(int(d.inclusion_delay) + 3, int(spec.SLOTS_PER_EPOCH)))
        dupes.append(d)
    state.previous_epoch_attestations = \
        list(state.previous_epoch_attestations) + dupes
    yield "pre", state.copy()
    yield from _emit_deltas(spec, state)
