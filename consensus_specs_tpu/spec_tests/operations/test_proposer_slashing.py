"""process_proposer_slashing operation tests."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, always_bls)
from ...test_infra.slashings import get_valid_proposer_slashing


def run_proposer_slashing_processing(spec, state, proposer_slashing,
                                     valid=True):
    yield "pre", state.copy()
    yield "proposer_slashing", proposer_slashing
    if not valid:
        try:
            spec.process_proposer_slashing(state, proposer_slashing)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("proposer slashing unexpectedly valid")
    spec.process_proposer_slashing(state, proposer_slashing)
    slashed_index = int(
        proposer_slashing.signed_header_1.message.proposer_index)
    # NOTE: no strict balance-decrease assert — when the slashed validator
    # is also the block proposer (as here), electra's EIP-7251 quotients
    # make penalty and whistleblower reward cancel exactly
    assert state.validators[slashed_index].slashed
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_proposer_slashing(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    yield from run_proposer_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1(spec, state):
    slashing = get_valid_proposer_slashing(
        spec, state, signed_1=False, signed_2=True)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_identical_headers(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    slashing.signed_header_2 = slashing.signed_header_1
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_not_slashable(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    index = int(slashing.signed_header_1.message.proposer_index)
    state.validators[index].slashed = True
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


from ...ssz import uint64  # noqa: E402
from ...test_infra.keys import privkey_for_pubkey  # noqa: E402
from ...test_infra.slashings import sign_block_header  # noqa: E402
from ...test_infra.context import (  # noqa: E402
    with_pytest_fork_subset)




@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_slashed_and_proposer_index_the_same(spec, state):
    """Slash the validator who is ALSO the next block proposer."""
    proposer = int(spec.get_beacon_proposer_index(state))
    slashing = get_valid_proposer_slashing(spec, state,
                                           proposer_index=proposer)
    yield from run_proposer_slashing_processing(spec, state, slashing)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_block_header_from_future(spec, state):
    """Headers at a future slot are still slashable evidence."""
    slashing = get_valid_proposer_slashing(spec, state)
    future = uint64(int(state.slot) + 5)
    index = int(slashing.signed_header_1.message.proposer_index)
    privkey = privkey_for_pubkey(state.validators[index].pubkey)
    for which in ("signed_header_1", "signed_header_2"):
        header = getattr(slashing, which).message
        header.slot = future
        setattr(slashing, which,
                sign_block_header(spec, state, header, privkey))
    yield from run_proposer_slashing_processing(spec, state, slashing)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
@always_bls
def test_invalid_sig_2(spec, state):
    slashing = get_valid_proposer_slashing(
        spec, state, signed_1=True, signed_2=False)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
@always_bls
def test_invalid_sig_1_and_2_swap(spec, state):
    """Swap the two (valid) signatures between the headers."""
    slashing = get_valid_proposer_slashing(spec, state)
    s1 = slashing.signed_header_1.signature
    slashing.signed_header_1.signature = \
        slashing.signed_header_2.signature
    slashing.signed_header_2.signature = s1
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_proposer_index_out_of_range(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    high = len(state.validators)
    for sh in (slashing.signed_header_1, slashing.signed_header_2):
        sh.message.proposer_index = uint64(high)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_different_proposer_indices(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    other = (int(slashing.signed_header_1.message.proposer_index) + 1) \
        % len(state.validators)
    header = slashing.signed_header_2.message
    header.proposer_index = uint64(other)
    slashing.signed_header_2 = sign_block_header(
        spec, state, header,
        privkey_for_pubkey(state.validators[other].pubkey))
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_slots_of_different_epochs(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    index = int(slashing.signed_header_1.message.proposer_index)
    privkey = privkey_for_pubkey(state.validators[index].pubkey)
    header = slashing.signed_header_2.message
    header.slot = uint64(int(header.slot) + int(spec.SLOTS_PER_EPOCH))
    slashing.signed_header_2 = sign_block_header(spec, state, header,
                                                 privkey)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_proposer_is_not_activated(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    index = int(slashing.signed_header_1.message.proposer_index)
    state.validators[index].activation_epoch = uint64(
        int(spec.get_current_epoch(state)) + 2)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_proposer_is_withdrawn(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    index = int(slashing.signed_header_1.message.proposer_index)
    cur = int(spec.get_current_epoch(state))
    state.validators[index].exit_epoch = uint64(max(cur - 1, 0))
    state.validators[index].withdrawable_epoch = uint64(cur)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_incorrect_sig_1_and_2(spec, state):
    slashing = get_valid_proposer_slashing(
        spec, state, signed_1=False, signed_2=False)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_headers_are_same_sigs_are_same(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    slashing.signed_header_2 = slashing.signed_header_1.copy()
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_headers_are_same_sigs_are_different(spec, state):
    """Identical header messages with differing signature bytes are
    still the SAME header — not slashable."""
    slashing = get_valid_proposer_slashing(spec, state)
    slashing.signed_header_2 = slashing.signed_header_1.copy()
    sig = bytearray(bytes(slashing.signed_header_2.signature))
    sig[5] ^= 0xFF
    slashing.signed_header_2.signature = bytes(sig)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_is_slashed(spec, state):
    """An already-slashed proposer is no longer slashable."""
    slashing = get_valid_proposer_slashing(spec, state)
    index = int(slashing.signed_header_1.message.proposer_index)
    state.validators[index].slashed = True
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)
