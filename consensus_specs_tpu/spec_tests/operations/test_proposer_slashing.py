"""process_proposer_slashing operation tests."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, always_bls)
from ...test_infra.slashings import get_valid_proposer_slashing


def run_proposer_slashing_processing(spec, state, proposer_slashing,
                                     valid=True):
    yield "pre", state.copy()
    yield "proposer_slashing", proposer_slashing
    if not valid:
        try:
            spec.process_proposer_slashing(state, proposer_slashing)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("proposer slashing unexpectedly valid")
    spec.process_proposer_slashing(state, proposer_slashing)
    slashed_index = int(
        proposer_slashing.signed_header_1.message.proposer_index)
    # NOTE: no strict balance-decrease assert — when the slashed validator
    # is also the block proposer (as here), electra's EIP-7251 quotients
    # make penalty and whistleblower reward cancel exactly
    assert state.validators[slashed_index].slashed
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_proposer_slashing(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    yield from run_proposer_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1(spec, state):
    slashing = get_valid_proposer_slashing(
        spec, state, signed_1=False, signed_2=True)
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_identical_headers(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    slashing.signed_header_2 = slashing.signed_header_1
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_not_slashable(spec, state):
    slashing = get_valid_proposer_slashing(spec, state)
    index = int(slashing.signed_header_1.message.proposer_index)
    state.validators[index].slashed = True
    yield from run_proposer_slashing_processing(
        spec, state, slashing, valid=False)
