"""process_deposit operation tests (merkle proof + signature paths)."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, with_all_phases_from)
from ...test_infra.deposits import (
    prepare_state_and_deposit, run_deposit_processing)


@with_all_phases
@spec_state_test
def test_new_deposit_under_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - 1
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up_max_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_new_deposit_invalid_sig(spec, state):
    """An unsigned new-validator deposit is VALID to process but not
    effective (no validator added) — pre-electra semantics; electra defers
    the signature check to pending-deposit application."""
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=False)
    yield from run_deposit_processing(spec, state, deposit, validator_index,
                                      effective=False)


@with_all_phases
@spec_state_test
def test_invalid_deposit_proof(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    deposit.proof[3] = b"\x55" * 32
    yield from run_deposit_processing(spec, state, deposit, validator_index,
                                      valid=False)
