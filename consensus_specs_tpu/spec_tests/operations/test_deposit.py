"""process_deposit operation tests (merkle proof + signature paths)."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, with_all_phases_from)
from ...test_infra.deposits import (
    prepare_state_and_deposit, run_deposit_processing)


@with_all_phases
@spec_state_test
def test_new_deposit_under_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - 1
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up_max_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_new_deposit_invalid_sig(spec, state):
    """An unsigned new-validator deposit is VALID to process but not
    effective (no validator added) — pre-electra semantics; electra defers
    the signature check to pending-deposit application."""
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=False)
    yield from run_deposit_processing(spec, state, deposit, validator_index,
                                      effective=False)


@with_all_phases
@spec_state_test
def test_invalid_deposit_proof(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    deposit.proof[3] = b"\x55" * 32
    yield from run_deposit_processing(spec, state, deposit, validator_index,
                                      valid=False)


from ...ssz import uint64  # noqa: E402
from ...test_infra.context import (  # noqa: E402
    always_bls, never_bls)


@with_all_phases
@spec_state_test
def test_new_deposit_max(spec, state):
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_over_max(spec, state):
    """Deposits above the max effective balance are accepted; the
    excess stays as plain balance."""
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index,
        uint64(int(spec.MAX_EFFECTIVE_BALANCE) + 10**9), signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_eth1_withdrawal_credentials(spec, state):
    validator_index = len(state.validators)
    creds = b"\x01" + b"\x00" * 11 + b"\x42" * 20
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        withdrawal_credentials=creds, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_non_versioned_withdrawal_credentials(spec, state):
    """Arbitrary credential prefixes are NOT validated at deposit
    time (only at withdrawal)."""
    validator_index = len(state.validators)
    creds = b"\xff" + b"\x02" * 31
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        withdrawal_credentials=creds, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_top_up_less_than_min_activation(spec, state):
    validator_index = 1
    amount = uint64(10**9)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
@never_bls
def test_top_up_invalid_sig(spec, state):
    """Top-ups skip the signature check entirely (pre-electra
    immediate; electra checks at queue application against the
    EXISTING validator)."""
    validator_index = 0
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, uint64(10**9), signed=False)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
@always_bls
def test_new_deposit_wrong_pubkey_sig(spec, state):
    """A garbage signature on a NEW pubkey: the deposit processes but
    takes no effect on any fork (pre-electra: no validator added;
    electra: nothing queued)."""
    validator_index = len(state.validators)
    # stage normally then overwrite the signature (and restage the
    # eth1 root, which commits to the data incl. signature)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    deposit.data.signature = b"\x99" * 96
    # the eth1 root commits to the data incl. signature: restage
    from ...test_infra.deposits import deposit_tree
    root, _leaves = deposit_tree(spec, [deposit.data])
    from ...ssz.merkle import get_merkle_proof
    limit = 2 ** spec.DEPOSIT_CONTRACT_TREE_DEPTH
    proof = get_merkle_proof(_leaves, 0, limit=limit) + [
        (1).to_bytes(32, "little")]
    deposit.proof = proof
    state.eth1_data.deposit_root = root
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False)


@with_all_phases
@spec_state_test
def test_invalid_deposit_index_mismatch(spec, state):
    """eth1_deposit_index pointing past the staged deposit breaks the
    merkle branch."""
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    state.eth1_deposit_index = uint64(1)
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_deposit_short_proof(spec, state):
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    deposit.proof = deposit.proof[:-1] + [b"\x00" * 32]
    deposit.proof[-1] = b"\x07" * 32
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)


# ---------------------------------------------------------------------------
# signature/key/fork-version long tail (reference
# test_process_deposit.py)
# ---------------------------------------------------------------------------

from ...ssz import Bytes32, uint64  # noqa: E402
from ...test_infra.deposits import build_deposit_data  # noqa: E402
from ...test_infra.keys import pubkeys, privkeys  # noqa: E402

_PUBKEY_NOT_IN_SUBGROUP = bytes.fromhex(
    "8123456789abcdef0123456789abcdef0123456789abcdef"
    "0123456789abcdef0123456789abcdef0123456789abcdef")
_PUBKEY_NOT_DECOMPRESSIBLE = bytes.fromhex(
    "8123456789abcdef0123456789abcdef0123456789abcdef"
    "0123456789abcdef0123456789abcdef0123456789abcde0")


def _deposit_with_pubkey(spec, state, pubkey, amount):
    """A deposit for an arbitrary (possibly invalid) pubkey with a
    valid merkle proof and a garbage signature."""
    creds = bytes(spec.BLS_WITHDRAWAL_PREFIX) \
        + bytes(spec.hash(pubkey))[1:]
    data = spec.DepositData(
        pubkey=pubkey, withdrawal_credentials=Bytes32(creds),
        amount=uint64(amount), signature=b"\x11" + b"\x00" * 95)
    leaves = [data]
    deposit, root = build_deposit_from_list(spec, leaves, 0)
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = uint64(len(leaves))
    state.eth1_deposit_index = uint64(0)
    return deposit


def build_deposit_from_list(spec, data_list, index):
    from ...test_infra.deposits import deposit_tree
    from ...ssz.merkle import get_merkle_proof
    root, leaves = deposit_tree(spec, data_list)
    limit = 2 ** spec.DEPOSIT_CONTRACT_TREE_DEPTH
    proof = get_merkle_proof(leaves, index, limit=limit) + [
        int(len(leaves)).to_bytes(32, "little")]
    return spec.Deposit(proof=proof, data=data_list[index]), root


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_key_validate_invalid_subgroup(spec, state):
    """A pubkey outside the G1 subgroup: KeyValidate fails, the deposit
    processes but adds no validator (pre-electra semantics)."""
    index = len(state.validators)
    deposit = _deposit_with_pubkey(
        spec, state, _PUBKEY_NOT_IN_SUBGROUP,
        int(spec.MAX_EFFECTIVE_BALANCE))
    yield from run_deposit_processing(spec, state, deposit, index,
                                      effective=False)


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_key_validate_invalid_decompression(spec, state):
    index = len(state.validators)
    deposit = _deposit_with_pubkey(
        spec, state, _PUBKEY_NOT_DECOMPRESSIBLE,
        int(spec.MAX_EFFECTIVE_BALANCE))
    yield from run_deposit_processing(spec, state, deposit, index,
                                      effective=False)


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_incorrect_withdrawal_credentials_top_up(spec, state):
    """Top-up with mismatched credentials still credits the balance
    (credentials were pinned at first deposit)."""
    validator_index = 0
    amount = int(spec.MAX_EFFECTIVE_BALANCE) // 4
    wrong_creds = bytes(spec.BLS_WITHDRAWAL_PREFIX) \
        + bytes(spec.hash(b"l" * 48))[1:]
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True,
        withdrawal_credentials=wrong_creds)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_top_up__zero_balance(spec, state):
    validator_index = 0
    state.balances[validator_index] = 0
    state.validators[validator_index].effective_balance = 0
    amount = int(spec.MAX_EFFECTIVE_BALANCE) // 4
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_top_up__less_effective_balance(spec, state):
    validator_index = 0
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.validators[validator_index].effective_balance = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE) - incr)
    state.balances[validator_index] = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE) - incr)
    amount = int(spec.MAX_EFFECTIVE_BALANCE) // 4
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_correct_sig_but_forked_state(spec, state):
    """Deposits pin the GENESIS fork version: a mangled state fork
    changes nothing."""
    index = len(state.validators)
    state.fork.current_version = b"\x12\x34\xab\xcd"
    deposit = prepare_state_and_deposit(
        spec, state, index, int(spec.MAX_EFFECTIVE_BALANCE),
        signed=True)
    yield from run_deposit_processing(spec, state, deposit, index)


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_ineffective_deposit_with_bad_fork_version(spec, state):
    """Signed over a bogus fork version: processes but adds nothing."""
    from ...utils import bls as _bls
    index = len(state.validators)
    pubkey = pubkeys[index]
    creds = bytes(spec.BLS_WITHDRAWAL_PREFIX) \
        + bytes(spec.hash(pubkey))[1:]
    message = spec.DepositMessage(
        pubkey=pubkey, withdrawal_credentials=Bytes32(creds),
        amount=uint64(int(spec.MAX_EFFECTIVE_BALANCE)))
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT,
                                 b"\xaa\xbb\xcc\xdd", Bytes32())
    signature = _bls.Sign(privkeys[index],
                          spec.compute_signing_root(message, domain))
    data = spec.DepositData(
        pubkey=pubkey, withdrawal_credentials=Bytes32(creds),
        amount=uint64(int(spec.MAX_EFFECTIVE_BALANCE)),
        signature=signature)
    deposit, root = build_deposit_from_list(spec, [data], 0)
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = uint64(1)
    state.eth1_deposit_index = uint64(0)
    yield from run_deposit_processing(spec, state, deposit, index,
                                      effective=False)


@with_all_phases
@spec_state_test
def test_invalid_wrong_deposit_for_deposit_count(spec, state):
    """Proof built against leaf 1 while the state expects leaf 0."""
    from ...test_infra.deposits import build_deposit_data
    creds = bytes(spec.BLS_WITHDRAWAL_PREFIX) + b"\x00" * 31
    data_0 = build_deposit_data(
        spec, pubkeys[len(state.validators)],
        privkeys[len(state.validators)],
        int(spec.MAX_EFFECTIVE_BALANCE), creds, signed=True)
    data_1 = build_deposit_data(
        spec, pubkeys[len(state.validators) + 1],
        privkeys[len(state.validators) + 1],
        int(spec.MAX_EFFECTIVE_BALANCE), creds, signed=True)
    deposit, root = build_deposit_from_list(spec, [data_0, data_1], 1)
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = uint64(2)
    state.eth1_deposit_index = uint64(0)   # expects leaf 0, given leaf 1
    yield from run_deposit_processing(
        spec, state, deposit, len(state.validators), valid=False)
