"""process_deposit operation tests (merkle proof + signature paths)."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, with_all_phases_from)
from ...test_infra.deposits import (
    prepare_state_and_deposit, run_deposit_processing)


@with_all_phases
@spec_state_test
def test_new_deposit_under_max(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - 1
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases
@spec_state_test
def test_top_up_max_effective_balance(spec, state):
    validator_index = 0
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit, validator_index)


@with_all_phases_from("phase0", to="deneb")
@spec_state_test
def test_new_deposit_invalid_sig(spec, state):
    """An unsigned new-validator deposit is VALID to process but not
    effective (no validator added) — pre-electra semantics; electra defers
    the signature check to pending-deposit application."""
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=False)
    yield from run_deposit_processing(spec, state, deposit, validator_index,
                                      effective=False)


@with_all_phases
@spec_state_test
def test_invalid_deposit_proof(spec, state):
    validator_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    deposit.proof[3] = b"\x55" * 32
    yield from run_deposit_processing(spec, state, deposit, validator_index,
                                      valid=False)


from ...ssz import uint64  # noqa: E402
from ...test_infra.context import (  # noqa: E402
    always_bls, never_bls)


@with_all_phases
@spec_state_test
def test_new_deposit_max(spec, state):
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_over_max(spec, state):
    """Deposits above the max effective balance are accepted; the
    excess stays as plain balance."""
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index,
        uint64(int(spec.MAX_EFFECTIVE_BALANCE) + 10**9), signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_eth1_withdrawal_credentials(spec, state):
    validator_index = len(state.validators)
    creds = b"\x01" + b"\x00" * 11 + b"\x42" * 20
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        withdrawal_credentials=creds, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_new_deposit_non_versioned_withdrawal_credentials(spec, state):
    """Arbitrary credential prefixes are NOT validated at deposit
    time (only at withdrawal)."""
    validator_index = len(state.validators)
    creds = b"\xff" + b"\x02" * 31
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        withdrawal_credentials=creds, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
def test_top_up_less_than_min_activation(spec, state):
    validator_index = 1
    amount = uint64(10**9)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, amount, signed=True)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
@never_bls
def test_top_up_invalid_sig(spec, state):
    """Top-ups skip the signature check entirely (pre-electra
    immediate; electra checks at queue application against the
    EXISTING validator)."""
    validator_index = 0
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, uint64(10**9), signed=False)
    yield from run_deposit_processing(spec, state, deposit,
                                      validator_index)


@with_all_phases
@spec_state_test
@always_bls
def test_new_deposit_wrong_pubkey_sig(spec, state):
    """A garbage signature on a NEW pubkey: the deposit processes but
    takes no effect on any fork (pre-electra: no validator added;
    electra: nothing queued)."""
    validator_index = len(state.validators)
    # stage normally then overwrite the signature (and restage the
    # eth1 root, which commits to the data incl. signature)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    deposit.data.signature = b"\x99" * 96
    # the eth1 root commits to the data incl. signature: restage
    from ...test_infra.deposits import deposit_tree
    root, _leaves = deposit_tree(spec, [deposit.data])
    from ...ssz.merkle import get_merkle_proof
    limit = 2 ** spec.DEPOSIT_CONTRACT_TREE_DEPTH
    proof = get_merkle_proof(_leaves, 0, limit=limit) + [
        (1).to_bytes(32, "little")]
    deposit.proof = proof
    state.eth1_data.deposit_root = root
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, effective=False)


@with_all_phases
@spec_state_test
def test_invalid_deposit_index_mismatch(spec, state):
    """eth1_deposit_index pointing past the staged deposit breaks the
    merkle branch."""
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    state.eth1_deposit_index = uint64(1)
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_deposit_short_proof(spec, state):
    validator_index = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, validator_index, spec.MAX_EFFECTIVE_BALANCE,
        signed=True)
    deposit.proof = deposit.proof[:-1] + [b"\x00" * 32]
    deposit.proof[-1] = b"\x07" * 32
    yield from run_deposit_processing(
        spec, state, deposit, validator_index, valid=False)
