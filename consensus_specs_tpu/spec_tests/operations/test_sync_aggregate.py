"""process_sync_aggregate operation tests (altair+; reference:
test/altair/block_processing/sync_aggregate/*; vector format
tests/formats/operations)."""
import pytest

from ...gen.vector_test import SkippedTest
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, with_presets,
    with_pytest_fork_subset, always_bls)

# real-signature suite: the default PYTEST run covers two
# representative forks (32 committee signatures per target); the
# generator still emits vectors for every altair+ fork
SYNC_FORKS = ["altair", "electra"]
from ...test_infra.blocks import (
    build_empty_block_for_next_slot, next_slot, transition_to)
from ...test_infra.sync_committee import (
    get_sync_aggregate, run_sync_committee_processing,
    compute_aggregate_sync_committee_signature)


def _block_with_aggregate(spec, state, participation_fn=None):
    """Advance one slot and attach a valid aggregate signed for that
    slot."""
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    block.body.sync_aggregate = get_sync_aggregate(
        spec, state, participation_fn=participation_fn)
    return block


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_rewards_all_participating(spec, state):
    block = _block_with_aggregate(spec, state)
    pre_balances = list(state.balances)
    yield from run_sync_committee_processing(spec, state, block)
    # every participant is rewarded (committee members may repeat)
    assert sum(state.balances) > sum(pre_balances)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_half_participating(spec, state):
    block = _block_with_aggregate(spec, state,
                                  participation_fn=lambda p: p % 2 == 0)
    yield from run_sync_committee_processing(spec, state, block)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_no_participants(spec, state):
    """Empty participation with the infinity-point signature is valid
    (eth_fast_aggregate_verify special case)."""
    block = _block_with_aggregate(spec, state,
                                  participation_fn=lambda p: False)
    pre_balances = list(state.balances)
    yield from run_sync_committee_processing(spec, state, block)
    # everyone in the committee is penalized, no rewards
    assert sum(state.balances) < sum(pre_balances)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_bad_domain(spec, state):
    """The full committee signs the right root under the WRONG domain
    (attester domain instead of DOMAIN_SYNC_COMMITTEE)."""
    from ...ssz import uint64
    from ...test_infra.keys import privkey_for_pubkey
    from ...utils import bls
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    previous_slot = uint64(max(int(state.slot), 1) - 1)
    wrong_domain = spec.get_domain(
        state, spec.DOMAIN_BEACON_ATTESTER,
        spec.compute_epoch_at_slot(previous_slot))
    signing_root = spec.compute_signing_root(
        spec.get_block_root_at_slot(state, previous_slot), wrong_domain)
    sigs = [bls.Sign(privkey_for_pubkey(pk), signing_root)
            for pk in state.current_sync_committee.pubkeys]
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * int(spec.SYNC_COMMITTEE_SIZE),
        sync_committee_signature=bls.Aggregate(sigs))
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_corrupted(spec, state):
    """A correctly-domained aggregate with one flipped byte."""
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    agg = get_sync_aggregate(spec, state)
    sig = bytearray(bytes(agg.sync_committee_signature))
    sig[5] ^= 0xFF
    agg.sync_committee_signature = bytes(sig)
    block.body.sync_aggregate = agg
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_missing_participant(spec, state):
    """Bits claim full participation but one member didn't sign."""
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    sig = compute_aggregate_sync_committee_signature(
        spec, state, list(range(size - 1)))
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * size,
        sync_committee_signature=sig)
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_infinity_with_participants(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * size,
        sync_committee_signature=spec.G2_POINT_AT_INFINITY)
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_proposer_in_committee(spec, state):
    """Full participation across an extra slot so the proposer may be a
    participant; processing must stay consistent either way."""
    next_slot(spec, state)
    block = _block_with_aggregate(spec, state)
    yield from run_sync_committee_processing(spec, state, block)


def _aggregate_with(spec, state, bit_positions, signing_positions):
    """A SyncAggregate whose BITS and SIGNATURE cover different
    position sets — the invalid-signature battery's workhorse."""
    size = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [p in set(bit_positions) for p in range(size)]
    signature = compute_aggregate_sync_committee_signature(
        spec, state, list(signing_positions))
    return spec.SyncAggregate(sync_committee_bits=bits,
                              sync_committee_signature=signature)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_first_participant_missing(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    block.body.sync_aggregate = _aggregate_with(
        spec, state, range(size), range(1, size))
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_extra_participant(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    block.body.sync_aggregate = _aggregate_with(
        spec, state, range(1, size), range(size))
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_infinite_signature_with_single_participant(
        spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    block.body.sync_aggregate = _aggregate_with(spec, state, [0], [])
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@pytest.mark.slow  # wrong-committee signing under always_bls (~10 s each); the cheaper invalid-signature rows keep the quick signal
@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_past_block(spec, state):
    """An aggregate signed over a two-slots-old root fails (the
    signature covers the PREVIOUS slot's block root)."""
    from ...ssz import uint64
    from ...test_infra.blocks import apply_empty_block
    # real blocks so historical roots actually differ (empty slots all
    # repeat the previous block root, which would keep the stale
    # signature valid)
    apply_empty_block(spec, state)
    apply_empty_block(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    block.body.sync_aggregate = get_sync_aggregate(
        spec, state, signature_slot=uint64(int(state.slot) - 2))
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


def _committee_member_validator_index(spec, state, position=0):
    pubkey = state.current_sync_committee.pubkeys[position]
    for i, v in enumerate(state.validators):
        if v.pubkey == pubkey:
            return i
    raise AssertionError("sync committee pubkey not in registry")


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_with_participating_exited_member(spec, state):
    """An exited validator may keep signing sync duties; the aggregate
    stays valid."""
    from ...ssz import uint64
    index = _committee_member_validator_index(spec, state)
    state.validators[index].exit_epoch = uint64(
        int(spec.get_current_epoch(state)))
    block = _block_with_aggregate(spec, state)
    yield from run_sync_committee_processing(spec, state, block)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_with_nonparticipating_exited_member(spec, state):
    from ...ssz import uint64
    index = _committee_member_validator_index(spec, state)
    state.validators[index].exit_epoch = uint64(
        int(spec.get_current_epoch(state)))
    pubkey = state.validators[index].pubkey
    skip = {p for p, pk in
            enumerate(state.current_sync_committee.pubkeys)
            if pk == pubkey}
    block = _block_with_aggregate(
        spec, state, participation_fn=lambda p: p not in skip)
    yield from run_sync_committee_processing(spec, state, block)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_quarter_participating(spec, state):
    block = _block_with_aggregate(
        spec, state, participation_fn=lambda i: i % 4 == 0)
    yield from run_sync_committee_processing(spec, state, block)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_one_participant(spec, state):
    block = _block_with_aggregate(
        spec, state, participation_fn=lambda i: i == 0)
    yield from run_sync_committee_processing(spec, state, block)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_rewards_duplicate_committee_members(spec,
                                                           state):
    """Small registries may repeat members across the 32 seats; each
    SEAT earns independently (exact per-seat accounting holds either
    way)."""
    block = _block_with_aggregate(spec, state)
    pre = list(state.balances)
    yield from run_sync_committee_processing(spec, state, block)
    assert sum(state.balances) > sum(pre)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_sync_committee_nonparticipants_penalized(spec, state):
    """Non-participating seats take the mirrored penalty."""
    from ...test_infra.keys import privkey_for_pubkey
    keep = set(range(0, int(spec.SYNC_COMMITTEE_SIZE), 2))
    participants = {
        bytes(pk) for i, pk in
        enumerate(state.current_sync_committee.pubkeys) if i in keep}
    block = _block_with_aggregate(
        spec, state, participation_fn=lambda i: i in keep)
    # a validator whose EVERY seat is non-participating must lose
    all_seats = {}
    for i, pk in enumerate(state.current_sync_committee.pubkeys):
        all_seats.setdefault(bytes(pk), []).append(i in keep)
    never = [pk for pk, seats in all_seats.items()
             if not any(seats)]
    pre = {bytes(v.pubkey): int(state.balances[j])
           for j, v in enumerate(state.validators)}
    yield from run_sync_committee_processing(spec, state, block)
    post = {bytes(v.pubkey): int(state.balances[j])
            for j, v in enumerate(state.validators)}
    proposer = bytes(
        state.validators[
            int(spec.get_beacon_proposer_index(state))].pubkey)
    for pk in never:
        if pk != proposer:
            assert post[pk] < pre[pk]


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_invalid_signature_no_participants_nonzero_sig(spec, state):
    """Zero bits with a random (non-infinity) signature must fail."""
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[False] * int(spec.SYNC_COMMITTEE_SIZE),
        sync_committee_signature=b"\x11" + b"\x22" * 95)
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


def _advance_periods(spec, state, n: int) -> None:
    """process_slots to the first slot of the sync-committee period `n`
    periods ahead.  At genesis current == next (both derived from epoch
    0), so distinguishing committees requires crossing a boundary."""
    from ...ssz import uint64
    epochs_per_period = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    cur_epoch = int(spec.get_current_epoch(state))
    target_epoch = (cur_epoch // epochs_per_period + n) * epochs_per_period
    transition_to(spec, state,
                  uint64(target_epoch * int(spec.SLOTS_PER_EPOCH)))


@pytest.mark.slow  # wrong-committee signing under always_bls (~10 s each); the cheaper invalid-signature rows keep the quick signal
@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@with_presets(["minimal"], reason="period fast-forward too slow on mainnet")
@spec_state_test
@always_bls
def test_invalid_signature_next_committee(spec, state):
    """A signature by the NEXT committee over the current message
    fails (wrong key set).  One period past genesis so next != current
    (at genesis both committees are computed from epoch 0)."""
    from ...test_infra.keys import privkey_for_pubkey
    from ...test_infra.sync_committee import (
        compute_sync_committee_signing_root)
    from ...utils import bls as _bls
    _advance_periods(spec, state, 1)
    if list(state.next_sync_committee.pubkeys) == \
            list(state.current_sync_committee.pubkeys):
        raise SkippedTest(
            "current and next sync committees identical on this preset")
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    aggregate = get_sync_aggregate(spec, state)
    # re-sign with the NEXT committee's keys instead
    root = compute_sync_committee_signing_root(spec, state)
    sigs = [_bls.Sign(privkey_for_pubkey(pk), root)
            for pk in state.next_sync_committee.pubkeys]
    aggregate.sync_committee_signature = _bls.Aggregate(sigs)
    block.body.sync_aggregate = aggregate
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


@pytest.mark.slow  # wrong-committee signing under always_bls (~10 s each); the cheaper invalid-signature rows keep the quick signal
@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@with_presets(["minimal"], reason="period fast-forward too slow on mainnet")
@spec_state_test
@always_bls
def test_invalid_signature_previous_committee(spec, state):
    """A committee that has rotated out (now 'previous') signs a block
    two periods later: wrong key set, must fail.  Two boundaries are
    needed because the genesis committee serves the first TWO periods
    (current == next at genesis).  Reference namesake:
    test/altair/block_processing/sync_aggregate/
    test_process_sync_aggregate.py (period-boundary variant)."""
    from ...test_infra.keys import privkey_for_pubkey
    from ...test_infra.sync_committee import (
        compute_sync_committee_signing_root)
    from ...utils import bls as _bls
    _advance_periods(spec, state, 1)
    old_committee = list(state.current_sync_committee.pubkeys)
    _advance_periods(spec, state, 1)
    if old_committee == list(state.current_sync_committee.pubkeys):
        raise SkippedTest("committee did not rotate on this preset")
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    aggregate = get_sync_aggregate(spec, state)
    root = compute_sync_committee_signing_root(spec, state)
    sigs = [_bls.Sign(privkey_for_pubkey(pk), root)
            for pk in old_committee]
    aggregate.sync_committee_signature = _bls.Aggregate(sigs)
    block.body.sync_aggregate = aggregate
    yield from run_sync_committee_processing(spec, state, block,
                                             valid=False)


# ---------------------------------------------------------------------------
# randomized participation (reference
# test_process_sync_aggregate_random.py; the minimal-preset committee
# repeats validators, i.e. the reference's *_with_duplicates arm)
# ---------------------------------------------------------------------------

import random as _random  # noqa: E402


def _run_random_participation(spec, state, seed, select_fn,
                              mutate_state=None):
    rng = _random.Random(f"{spec.fork}:{seed}")
    if mutate_state is not None:
        mutate_state(rng)
    block = build_empty_block_for_next_slot(spec, state)
    transition_to(spec, state, block.slot)
    committee_size = int(spec.SYNC_COMMITTEE_SIZE)
    chosen = select_fn(rng, committee_size)
    block.body.sync_aggregate = get_sync_aggregate(
        spec, state, participation_fn=lambda p: p in chosen)
    yield from run_sync_committee_processing(spec, state, block)


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_random_only_one_participant_with_duplicates(spec, state):
    yield from _run_random_participation(
        spec, state, "one",
        lambda rng, n: {rng.randrange(n)})


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_random_low_participation_with_duplicates(spec, state):
    yield from _run_random_participation(
        spec, state, "low",
        lambda rng, n: set(rng.sample(range(n), max(1, n // 4))))


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_random_high_participation_with_duplicates(spec, state):
    yield from _run_random_participation(
        spec, state, "high",
        lambda rng, n: set(rng.sample(range(n), max(1, 3 * n // 4))))


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_random_all_but_one_participating_with_duplicates(spec, state):
    yield from _run_random_participation(
        spec, state, "allbutone",
        lambda rng, n: set(range(n)) - {rng.randrange(n)})


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_random_half_participation_with_duplicates(spec, state):
    yield from _run_random_participation(
        spec, state, "half",
        lambda rng, n: set(rng.sample(range(n), n // 2)))


@with_all_phases_from("altair")
@with_pytest_fork_subset(SYNC_FORKS)
@spec_state_test
@always_bls
def test_random_with_exits_with_duplicates(spec, state):
    """Exited-but-unwithdrawn committee members still sign."""
    from ...ssz import uint64 as _u64
    def exit_some(rng):
        cur = int(spec.get_current_epoch(state))
        for i in range(0, len(state.validators), 7):
            state.validators[i].exit_epoch = _u64(max(cur, 1))
            state.validators[i].withdrawable_epoch = _u64(cur + 10)
    yield from _run_random_participation(
        spec, state, "exits",
        lambda rng, n: set(rng.sample(range(n), n // 2)),
        mutate_state=exit_some)
