"""process_execution_payload operation tests (bellatrix+; reference:
test/bellatrix/block_processing/test_process_execution_payload.py
shape).  The noop engine answers True, so the consensus-side asserts
(parent hash, randao, timestamp, blob commitment limits) are under
test."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases_from
from ...test_infra.blocks import build_empty_execution_payload


def _body_for(spec, payload, commitments=None):
    body = spec.BeaconBlockBody()
    body.execution_payload = payload
    if commitments is not None:
        body.blob_kzg_commitments = commitments
    return body


def _run(spec, state, payload, valid=True, commitments=None):
    # bellatrix's process_execution_payload takes the body (deneb needs
    # the commitments); emit the payload for the vector
    body = _body_for(spec, payload, commitments)
    yield "pre", state.copy()
    yield "execution_payload", payload
    if not valid:
        try:
            spec.process_execution_payload(state, body,
                                           spec.EXECUTION_ENGINE)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("payload unexpectedly valid")
    spec.process_execution_payload(state, body, spec.EXECUTION_ENGINE)
    yield "post", state


@with_all_phases_from("bellatrix")
@spec_state_test
def test_success_empty_payload(spec, state):
    payload = build_empty_execution_payload(spec, state)
    yield from _run(spec, state, payload)
    assert state.latest_execution_payload_header.block_hash == \
        payload.block_hash


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_parent_hash(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_prev_randao(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = b"\x42" * 32
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_timestamp(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = uint64(int(payload.timestamp) + 1)
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("deneb")
@spec_state_test
def test_invalid_too_many_blob_commitments(spec, state):
    payload = build_empty_execution_payload(spec, state)
    limit = int(spec.max_blobs_per_block())
    commitments = [b"\xc0" + b"\x00" * 47] * (limit + 1)
    yield from _run(spec, state, payload, valid=False,
                    commitments=commitments)


@with_all_phases_from("deneb")
@spec_state_test
def test_blob_commitments_at_limit(spec, state):
    payload = build_empty_execution_payload(spec, state)
    limit = int(spec.max_blobs_per_block())
    commitments = [b"\xc0" + b"\x00" * 47] * limit
    yield from _run(spec, state, payload, commitments=commitments)
