"""process_execution_payload operation tests (bellatrix+; reference:
test/bellatrix/block_processing/test_process_execution_payload.py
shape).

Vector format follows the reference operations format
(tests/formats/operations/README.md): the input is the full
``BeaconBlockBody`` yielded as ``body`` (deneb+ blob commitments live in
the body, so a payload-only input would be unrepresentable), plus an
``execution.yaml`` ``{execution_valid: bool}`` telling the consumer what
the mocked execution engine answered (the reference generator also
writes ``name + '.yaml'`` — gen_runner.py:382 — despite the format
README calling it execution.yml).
"""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, with_phases)
from ...test_infra.blocks import build_empty_execution_payload


class _MockExecutionEngine:
    """Engine double answering a fixed verdict (reference mocks the
    engine the same way to test the ``execution_valid=False`` path)."""

    def __init__(self, inner, valid: bool):
        self._inner = inner
        self._valid = valid

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        return self._valid

    def __getattr__(self, item):
        return getattr(self._inner, item)


def _body_for(spec, payload, commitments=None):
    body = spec.BeaconBlockBody()
    body.execution_payload = payload
    if commitments is not None:
        body.blob_kzg_commitments = commitments
    return body


def _run(spec, state, payload, valid=True, commitments=None,
         execution_valid=True):
    body = _body_for(spec, payload, commitments)
    yield "pre", state.copy()
    yield "execution", "cfg", {"execution_valid": execution_valid}
    yield "body", body
    engine = _MockExecutionEngine(spec.EXECUTION_ENGINE, execution_valid)
    if not (valid and execution_valid):
        try:
            spec.process_execution_payload(state, body, engine)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("payload unexpectedly valid")
    spec.process_execution_payload(state, body, engine)
    yield "post", state


@with_all_phases_from("bellatrix")
@spec_state_test
def test_success_empty_payload(spec, state):
    payload = build_empty_execution_payload(spec, state)
    yield from _run(spec, state, payload)
    assert state.latest_execution_payload_header.block_hash == \
        payload.block_hash


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_execution_engine_verdict(spec, state):
    # consensus-side checks all pass; the (mocked) engine rejects
    payload = build_empty_execution_payload(spec, state)
    yield from _run(spec, state, payload, execution_valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_parent_hash(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_prev_randao(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = b"\x42" * 32
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_timestamp(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = uint64(int(payload.timestamp) + 1)
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("deneb")
@spec_state_test
def test_invalid_too_many_blob_commitments(spec, state):
    payload = build_empty_execution_payload(spec, state)
    limit = int(spec.max_blobs_per_block())
    commitments = [b"\xc0" + b"\x00" * 47] * (limit + 1)
    yield from _run(spec, state, payload, valid=False,
                    commitments=commitments)


@with_all_phases_from("deneb")
@spec_state_test
def test_blob_commitments_at_limit(spec, state):
    payload = build_empty_execution_payload(spec, state)
    limit = int(spec.max_blobs_per_block())
    commitments = [b"\xc0" + b"\x00" * 47] * limit
    yield from _run(spec, state, payload, commitments=commitments)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_success_first_payload(spec, state):
    """The merge-transition block: pre-merge header, first payload."""
    if spec.is_post("capella"):
        # capella+ states are always post-merge; covered by regular
        payload = build_empty_execution_payload(spec, state)
        yield from _run(spec, state, payload)
        return
    state.latest_execution_payload_header = \
        spec.ExecutionPayloadHeader()
    assert not spec.is_merge_transition_complete(state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x41" * 32
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_success_regular_payload_with_gap_slot(spec, state):
    from ...test_infra.blocks import transition_to
    transition_to(spec, state, uint64(int(state.slot) + 3))
    payload = build_empty_execution_payload(spec, state)
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_non_empty_extra_data(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.extra_data = b"\x45" * 12
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_non_empty_transactions(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.transactions = [b"\x02" + b"\x99" * 30 for _ in range(3)]
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_zero_length_transaction(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.transactions = [b""]
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_randomized_non_validated_execution_fields(spec, state):
    """Consensus never inspects fee/gas/bloom contents — randomize
    them all."""
    import random as _r
    rng = _r.Random(f"{spec.fork}:payload-fields")
    payload = build_empty_execution_payload(spec, state)
    payload.fee_recipient = bytes(rng.randrange(256) for _ in range(20))
    payload.state_root = bytes(rng.randrange(256) for _ in range(32))
    payload.receipts_root = bytes(rng.randrange(256) for _ in range(32))
    payload.logs_bloom = bytes(
        rng.randrange(256) for _ in range(int(spec.BYTES_PER_LOGS_BLOOM)))
    payload.gas_limit = uint64(rng.randrange(1, 2**32))
    payload.gas_used = uint64(rng.randrange(0, 2**32))
    payload.base_fee_per_gas = rng.randrange(0, 2**64)
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_future_timestamp(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = uint64(int(payload.timestamp) + 12)
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_past_timestamp(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = uint64(max(int(payload.timestamp) - 12, 0))
    payload.block_hash = spec.hash(
        bytes(spec.hash_tree_root(payload)) + b"FAKE RLP HASH")
    yield from _run(spec, state, payload, valid=False)


# ---------------------------------------------------------------------------
# first-vs-regular payload matrix (reference bellatrix battery: the
# merge-transition block's FIRST payload skips the parent-hash link)
# ---------------------------------------------------------------------------

from ...test_infra.pow_block import (  # noqa: E402
    build_state_with_incomplete_transition)


def _first_payload_state(spec, state):
    return build_state_with_incomplete_transition(spec, state)


@with_phases(["bellatrix"])
@spec_state_test
def test_success_first_payload_pre_merge(spec, state):
    """The transition block's payload: parent-hash link not enforced —
    bellatrix only (capella made the check unconditional)."""
    state = _first_payload_state(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix", to="capella")
@spec_state_test
def test_success_first_payload_with_gap_slot(spec, state):
    state = _first_payload_state(spec, state)
    spec.process_slots(state, uint64(int(state.slot) + 2))
    payload = build_empty_execution_payload(spec, state)
    yield from _run(spec, state, payload)


@with_all_phases_from("bellatrix", to="capella")
@spec_state_test
def test_invalid_bad_prev_randao_first_payload(spec, state):
    state = _first_payload_state(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = b"\x42" * 32
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix", to="capella")
@spec_state_test
def test_invalid_future_timestamp_first_payload(spec, state):
    state = _first_payload_state(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = uint64(int(payload.timestamp) + 1)
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix", to="capella")
@spec_state_test
def test_invalid_past_timestamp_first_payload(spec, state):
    state = _first_payload_state(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = uint64(max(int(payload.timestamp) - 1, 0))
    yield from _run(spec, state, payload, valid=False)


@with_all_phases_from("bellatrix", to="capella")
@spec_state_test
def test_invalid_bad_execution_first_payload(spec, state):
    state = _first_payload_state(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from _run(spec, state, payload, execution_valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_bad_execution_regular_payload(spec, state):
    payload = build_empty_execution_payload(spec, state)
    yield from _run(spec, state, payload, execution_valid=False)


@with_all_phases_from("bellatrix", to="capella")
@spec_state_test
def test_invalid_bad_everything_first_payload(spec, state):
    state = _first_payload_state(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = b"\x42" * 32
    payload.timestamp = uint64(0 if int(payload.timestamp) else 1)
    yield from _run(spec, state, payload, valid=False,
                    execution_valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_invalid_bad_everything_regular_payload(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.prev_randao = b"\x42" * 32
    yield from _run(spec, state, payload, valid=False,
                    execution_valid=False)


@with_all_phases_from("bellatrix")
@spec_state_test
def test_non_empty_extra_data_regular_payload(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.extra_data = b"\x45" * 12
    yield from _run(spec, state, payload)
    assert bytes(
        state.latest_execution_payload_header.extra_data) == b"\x45" * 12


@with_all_phases_from("bellatrix")
@spec_state_test
def test_non_empty_transactions_regular_payload(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.transactions = [spec.Transaction(b"\x99" * 128)
                            for _ in range(2)]
    yield from _run(spec, state, payload)


# ---------------------------------------------------------------------------
# deneb blob-carrying payloads: the CL accepts shapes it cannot verify
# (the engine mock answers VALID; reference deneb battery)
# ---------------------------------------------------------------------------

def _fake_tx_and_commitments(spec, count=1, tx_type=0x03):
    opaque_tx = bytes([tx_type]) + b"\x9a" * 31
    commitments = [bytes([0x01 + i]) + b"\x00" * 47 for i in range(count)]
    return opaque_tx, commitments


@with_all_phases_from("deneb")
@spec_state_test
def test_incorrect_blob_tx_type(spec, state):
    """Wrong tx type byte: opaque to the CL, engine says VALID."""
    payload = build_empty_execution_payload(spec, state)
    opaque_tx, commitments = _fake_tx_and_commitments(spec, tx_type=0x04)
    payload.transactions = [opaque_tx]
    yield from _run(spec, state, payload, commitments=commitments)


@with_all_phases_from("deneb")
@spec_state_test
def test_incorrect_transaction_length_1_extra_byte(spec, state):
    payload = build_empty_execution_payload(spec, state)
    opaque_tx, commitments = _fake_tx_and_commitments(spec)
    payload.transactions = [opaque_tx + b"\x00"]
    yield from _run(spec, state, payload, commitments=commitments)


@with_all_phases_from("deneb")
@spec_state_test
def test_incorrect_transaction_length_1_byte_short(spec, state):
    payload = build_empty_execution_payload(spec, state)
    opaque_tx, commitments = _fake_tx_and_commitments(spec)
    payload.transactions = [opaque_tx[:-1]]
    yield from _run(spec, state, payload, commitments=commitments)


@with_all_phases_from("deneb")
@spec_state_test
def test_incorrect_transaction_length_empty(spec, state):
    payload = build_empty_execution_payload(spec, state)
    _, commitments = _fake_tx_and_commitments(spec)
    payload.transactions = [b""]
    yield from _run(spec, state, payload, commitments=commitments)


@with_all_phases_from("deneb")
@spec_state_test
def test_incorrect_commitments_order(spec, state):
    payload = build_empty_execution_payload(spec, state)
    opaque_tx, commitments = _fake_tx_and_commitments(spec, count=2)
    payload.transactions = [opaque_tx]
    yield from _run(spec, state, payload,
                    commitments=list(reversed(commitments)))


@with_all_phases_from("deneb")
@spec_state_test
def test_no_transactions_with_commitments(spec, state):
    payload = build_empty_execution_payload(spec, state)
    _, commitments = _fake_tx_and_commitments(spec)
    payload.transactions = []
    yield from _run(spec, state, payload, commitments=commitments)


@with_all_phases_from("deneb")
@spec_state_test
def test_zeroed_commitment(spec, state):
    payload = build_empty_execution_payload(spec, state)
    opaque_tx, _ = _fake_tx_and_commitments(spec)
    payload.transactions = [opaque_tx]
    yield from _run(spec, state, payload,
                    commitments=[b"\x00" * 48])


@with_all_phases_from("deneb")
@spec_state_test
def test_incorrect_block_hash(spec, state):
    """The CL itself never verifies the EL block hash."""
    payload = build_empty_execution_payload(spec, state)
    opaque_tx, commitments = _fake_tx_and_commitments(spec)
    payload.transactions = [opaque_tx]
    payload.block_hash = b"\x12" * 32
    yield from _run(spec, state, payload, commitments=commitments)
