"""process_attester_slashing operation tests."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, always_bls)
from ...test_infra.slashings import get_valid_attester_slashing


def run_attester_slashing_processing(spec, state, attester_slashing,
                                     valid=True):
    yield "pre", state.copy()
    yield "attester_slashing", attester_slashing
    if not valid:
        try:
            spec.process_attester_slashing(state, attester_slashing)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("attester slashing unexpectedly valid")
    slashable = [int(i) for i in
                 attester_slashing.attestation_1.attesting_indices]
    spec.process_attester_slashing(state, attester_slashing)
    assert any(state.validators[i].slashed for i in slashable)
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_double(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1(spec, state):
    slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_same_data(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    slashing.attestation_2 = slashing.attestation_1
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


from ...ssz import uint64  # noqa: E402
from ...test_infra.blocks import next_epoch  # noqa: E402
from ...test_infra.context import (  # noqa: E402
    low_balances, misc_balances, never_bls, with_custom_state,
    zero_activation_threshold)
from ...test_infra.context import (  # noqa: E402
    with_pytest_fork_subset)
from ...test_infra.slashings import (  # noqa: E402
    get_surround_attester_slashing, sign_indexed_attestation)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_basic_surround(spec, state):
    for _ in range(4):
        next_epoch(spec, state)
    slashing = get_surround_attester_slashing(spec, state)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_already_exited_recent(spec, state):
    """Recently-exited (not yet withdrawable) participants are still
    slashable."""
    slashing = get_valid_attester_slashing(spec, state)
    for i in slashing.attestation_1.attesting_indices:
        spec.initiate_validator_exit(state, uint64(int(i)))
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_already_exited_long_ago(spec, state):
    """Participants whose withdrawable epoch passed cannot be slashed:
    nothing newly slashed -> the operation is invalid."""
    slashing = get_valid_attester_slashing(spec, state)
    cur = int(spec.get_current_epoch(state))
    for i in slashing.attestation_1.attesting_indices:
        v = state.validators[int(i)]
        v.exit_epoch = uint64(max(cur - 2, 0) if cur >= 2 else 0)
        v.withdrawable_epoch = uint64(max(cur - 1, 0))
    yield from run_attester_slashing_processing(spec, state, slashing,
                                                valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_proposer_index_slashed(spec, state):
    """The next proposer being among the slashed set is fine for the
    operation itself."""
    slashing = get_valid_attester_slashing(spec, state)
    yield from run_attester_slashing_processing(spec, state, slashing)
    proposer = int(spec.get_beacon_proposer_index(state))
    slashable = [int(i) for i in
                 slashing.attestation_1.attesting_indices]
    # bookkeeping only: whether the proposer was hit is state-dependent
    assert all(state.validators[i].slashed for i in slashable) or \
        proposer >= 0


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@with_custom_state(balances_fn=low_balances,
                   threshold_fn=zero_activation_threshold)
@spec_state_test
@never_bls
def test_low_balances(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@with_custom_state(balances_fn=misc_balances,
                   threshold_fn=zero_activation_threshold)
@spec_state_test
@never_bls
def test_misc_balances(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
@always_bls
def test_invalid_sig_2(spec, state):
    slashing = get_valid_attester_slashing(
        spec, state, signed_1=True, signed_2=False)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
@always_bls
def test_invalid_sig_1_and_2(spec, state):
    slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=False)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_no_double_or_surround(spec, state):
    """Disjoint epochs with matching targets shifted: neither relation
    holds."""
    slashing = get_valid_attester_slashing(spec, state)
    # different target epochs, same source: not double, not surround
    slashing.attestation_2.data.target.epoch = uint64(
        int(slashing.attestation_1.data.target.epoch) + 1)
    sign_indexed_attestation(spec, state, slashing.attestation_2)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_participants_already_slashed(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    for i in slashing.attestation_1.attesting_indices:
        state.validators[int(i)].slashed = True
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


def _with_indices(spec, state, slashing, which, mutate):
    att = (slashing.attestation_1 if which == 1
           else slashing.attestation_2)
    indices = [int(i) for i in att.attesting_indices]
    att.attesting_indices = mutate(indices)
    sign_indexed_attestation(spec, state, att)
    return slashing


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_att1_high_index(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    high = len(state.validators)
    slashing.attestation_1.attesting_indices = [
        int(i) for i in slashing.attestation_1.attesting_indices
    ] + [high]
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_att2_high_index(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    high = len(state.validators)
    slashing.attestation_2.attesting_indices = [
        int(i) for i in slashing.attestation_2.attesting_indices
    ] + [high]
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_att1_empty_indices(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    slashing.attestation_1.attesting_indices = []
    slashing.attestation_1.signature = b"\xc0" + b"\x00" * 95
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_all_empty_indices(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    for att in (slashing.attestation_1, slashing.attestation_2):
        att.attesting_indices = []
        att.signature = b"\xc0" + b"\x00" * 95
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
@always_bls
def test_invalid_att1_bad_extra_index(spec, state):
    """A valid extra participant index whose key never signed."""
    slashing = get_valid_attester_slashing(spec, state)
    att = slashing.attestation_1
    indices = [int(i) for i in att.attesting_indices]
    extra = next(i for i in range(len(state.validators))
                 if i not in indices)
    att.attesting_indices = sorted(indices + [extra])
    # signature NOT rebuilt: the aggregate no longer matches
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
@always_bls
def test_invalid_att2_bad_replaced_index(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    att = slashing.attestation_2
    indices = [int(i) for i in att.attesting_indices]
    sub = next(i for i in range(len(state.validators))
               if i not in indices)
    indices[0] = sub
    att.attesting_indices = sorted(indices)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_unsorted_att_1(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    att = slashing.attestation_1
    indices = [int(i) for i in att.attesting_indices]
    if len(indices) < 2:
        return
    indices[0], indices[1] = indices[1], indices[0]
    att.attesting_indices = indices
    sign_indexed_attestation(spec, state, att)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_duplicate_index_att_2(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    att = slashing.attestation_2
    indices = [int(i) for i in att.attesting_indices]
    indices.append(indices[-1])
    att.attesting_indices = sorted(indices)
    sign_indexed_attestation(spec, state, att)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


# ---------------------------------------------------------------------------
# index-set corruption matrix (reference
# test_process_attester_slashing.py long tail)
# ---------------------------------------------------------------------------

@with_all_phases
@spec_state_test
def test_attestation_from_future(spec, state):
    """Double vote whose data sits at the state's current slot: still
    slashable (slashing has no inclusion-window check)."""
    slashing = get_valid_attester_slashing(spec, state,
                                           slot=state.slot)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_incorrect_sig_1(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    sig = bytearray(bytes(slashing.attestation_1.signature))
    sig[5] ^= 0xFF
    slashing.attestation_1.signature = bytes(sig)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_incorrect_sig_2(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    sig = bytearray(bytes(slashing.attestation_2.signature))
    sig[5] ^= 0xFF
    slashing.attestation_2.signature = bytes(sig)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_incorrect_sig_1_and_2(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    for att in (slashing.attestation_1, slashing.attestation_2):
        sig = bytearray(bytes(att.signature))
        sig[5] ^= 0xFF
        att.signature = bytes(sig)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_unsorted_att_2(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    indices = list(slashing.attestation_2.attesting_indices)
    if len(indices) < 2:
        from ...gen.vector_test import SkippedTest
        raise SkippedTest("committee too small to unsort")
    indices[0], indices[1] = indices[1], indices[0]
    slashing.attestation_2.attesting_indices = indices
    sign_indexed_attestation(spec, state, slashing.attestation_2)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_att1_bad_replaced_index(spec, state):
    """A non-committee index swapped in WITHOUT re-signing."""
    slashing = get_valid_attester_slashing(spec, state)
    indices = list(slashing.attestation_1.attesting_indices)
    indices[0] = len(state.validators) - 1
    slashing.attestation_1.attesting_indices = sorted(indices)
    # signature no longer matches the index set
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_att2_bad_extra_index(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    indices = list(slashing.attestation_2.attesting_indices)
    indices.append(len(state.validators) - 1)
    slashing.attestation_2.attesting_indices = sorted(indices)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_att1_duplicate_index_normal_signed(spec, state):
    """A duplicated index (still 'sorted' but not strictly unique)
    fails is_valid_indexed_attestation even when re-signed."""
    slashing = get_valid_attester_slashing(spec, state)
    indices = list(slashing.attestation_1.attesting_indices)
    indices.append(indices[0])
    slashing.attestation_1.attesting_indices = sorted(indices)
    sign_indexed_attestation(spec, state, slashing.attestation_1)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_att2_duplicate_index_normal_signed(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    indices = list(slashing.attestation_2.attesting_indices)
    indices.append(indices[0])
    slashing.attestation_2.attesting_indices = sorted(indices)
    sign_indexed_attestation(spec, state, slashing.attestation_2)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_att2_empty_indices(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    slashing.attestation_2.attesting_indices = []
    slashing.attestation_2.signature = b"\xc0" + b"\x00" * 95
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_with_effective_balance_disparity(spec, state):
    """Mixed effective balances across the slashed set: every member
    still slashed, penalties scale per balance."""
    slashing = get_valid_attester_slashing(spec, state)
    indices = [int(i) for i in
               slashing.attestation_1.attesting_indices]
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    for k, i in enumerate(indices):
        eb = int(spec.MAX_EFFECTIVE_BALANCE) - (k % 4) * incr
        state.validators[i].effective_balance = uint64(eb)
        state.balances[i] = uint64(eb)
    yield from run_attester_slashing_processing(spec, state, slashing)
    assert all(state.validators[i].slashed for i in indices)
