"""process_attester_slashing operation tests."""
from ...test_infra.context import (
    spec_state_test, with_all_phases, always_bls)
from ...test_infra.slashings import get_valid_attester_slashing


def run_attester_slashing_processing(spec, state, attester_slashing,
                                     valid=True):
    yield "pre", state.copy()
    yield "attester_slashing", attester_slashing
    if not valid:
        try:
            spec.process_attester_slashing(state, attester_slashing)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("attester slashing unexpectedly valid")
    slashable = [int(i) for i in
                 attester_slashing.attestation_1.attesting_indices]
    spec.process_attester_slashing(state, attester_slashing)
    assert any(state.validators[i].slashed for i in slashable)
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_double(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    yield from run_attester_slashing_processing(spec, state, slashing)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_sig_1(spec, state):
    slashing = get_valid_attester_slashing(
        spec, state, signed_1=False, signed_2=True)
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_same_data(spec, state):
    slashing = get_valid_attester_slashing(spec, state)
    slashing.attestation_2 = slashing.attestation_1
    yield from run_attester_slashing_processing(
        spec, state, slashing, valid=False)
