"""EIP-7251 EL-triggered consolidation request operation tests
(electra+).

Reference battery:
test/electra/block_processing/test_process_consolidation_request.py (32
cases).  Covers the consolidation path, the same-pubkey
switch-to-compounding path, and the no-fault ignored conditions for
both.
"""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, with_presets)
from ...test_infra.keys import pubkeys
from ...test_infra.withdrawals import (
    set_eth1_withdrawal_credentials,
    set_compounding_withdrawal_credentials)
from ...test_infra.electra_requests import (
    DEFAULT_ADDRESS, WRONG_ADDRESS, age_past_exit_gate, scale_churn,
    run_request_processing, make_exited, make_inactive,
    add_pending_partial_withdrawal)


def _stage(spec, state, source=0, target=1, source_compounding=False):
    """Eligible source (eth1 or compounding creds, aged) + compounding
    target + churn headroom."""
    age_past_exit_gate(spec, state)
    if source_compounding:
        set_compounding_withdrawal_credentials(spec, state, source,
                                               address=DEFAULT_ADDRESS)
    else:
        set_eth1_withdrawal_credentials(spec, state, source,
                                        address=DEFAULT_ADDRESS)
    set_compounding_withdrawal_credentials(spec, state, target)
    scale_churn(spec, state)


def _request(spec, state, source=0, target=1, address=DEFAULT_ADDRESS):
    return spec.ConsolidationRequest(
        source_address=address,
        source_pubkey=state.validators[source].pubkey,
        target_pubkey=state.validators[target].pubkey)


def _switch_request(spec, state, index, address=DEFAULT_ADDRESS):
    return _request(spec, state, index, index, address)


# ---------------------------------------------------------------------------
# successful consolidations
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_basic_consolidation(spec, state):
    _stage(spec, state)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1
    pc = state.pending_consolidations[0]
    assert (int(pc.source_index), int(pc.target_index)) == (0, 1)
    assert state.validators[0].exit_epoch != spec.FAR_FUTURE_EPOCH
    assert int(state.validators[0].withdrawable_epoch) == (
        int(state.validators[0].exit_epoch)
        + int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY))


@with_all_phases_from("electra")
@spec_state_test
def test_basic_consolidation_with_compounding_credentials(spec, state):
    _stage(spec, state, source_compounding=True)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_basic_consolidation_with_excess_target_balance(spec, state):
    _stage(spec, state)
    state.balances[1] = uint64(
        int(state.balances[1]) + int(spec.EFFECTIVE_BALANCE_INCREMENT))
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_basic_consolidation_with_preexisting_churn(spec, state):
    _stage(spec, state)
    # partially-consumed churn in the current consolidation epoch
    state.consolidation_balance_to_consume = uint64(
        int(spec.get_consolidation_churn_limit(state)) // 2)
    state.earliest_consolidation_epoch = uint64(
        int(spec.get_current_epoch(state)) + 1
        + int(spec.MAX_SEED_LOOKAHEAD))
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_consolidation_balance_larger_than_churn_limit(spec, state):
    # source effective balance above the per-epoch churn: exit epoch is
    # pushed past the earliest consolidation epoch
    _stage(spec, state)
    churn = int(spec.get_consolidation_churn_limit(state))
    state.validators[0].effective_balance = uint64(churn * 2)
    state.balances[0] = uint64(churn * 2)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1
    assert int(state.validators[0].exit_epoch) > int(
        spec.compute_activation_exit_epoch(
            spec.get_current_epoch(state)))


# ---------------------------------------------------------------------------
# switch-to-compounding (same pubkey)
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_basic_switch_to_compounding(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0))
    creds = bytes(state.validators[0].withdrawal_credentials)
    assert creds[:1] == bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX)
    # a switch is not a consolidation: nothing queued, no exit
    assert len(state.pending_consolidations) == 0
    assert state.validators[0].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_switch_to_compounding_with_excess(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    state.balances[0] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE)
        + int(spec.EFFECTIVE_BALANCE_INCREMENT))
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0))
    # the excess over MIN_ACTIVATION_BALANCE is queued as a deposit
    assert len(state.pending_deposits) == 1
    assert int(state.pending_deposits[0].amount) == \
        int(spec.EFFECTIVE_BALANCE_INCREMENT)


@with_all_phases_from("electra")
@with_presets(["minimal"], "filling the queue is preset-sized")
@spec_state_test
def test_switch_to_compounding_with_pending_consolidations_at_limit(
        spec, state):
    # the pending-consolidations limit does not gate the switch path
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    limit = int(spec.PENDING_CONSOLIDATIONS_LIMIT)
    for _ in range(limit):
        state.pending_consolidations.append(
            spec.PendingConsolidation(source_index=2, target_index=3))
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0))
    creds = bytes(state.validators[0].withdrawal_credentials)
    assert creds[:1] == bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX)


# ---------------------------------------------------------------------------
# ignored consolidations
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@with_presets(["minimal"], "filling the queue is preset-sized")
@spec_state_test
def test_incorrect_exceed_pending_consolidations_limit(spec, state):
    _stage(spec, state)
    limit = int(spec.PENDING_CONSOLIDATIONS_LIMIT)
    for _ in range(limit):
        state.pending_consolidations.append(
            spec.PendingConsolidation(source_index=2, target_index=3))
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_not_enough_consolidation_churn_available(spec, state):
    # unscaled registry: churn limit <= MIN_ACTIVATION_BALANCE
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    set_compounding_withdrawal_credentials(spec, state, 1)
    assert int(spec.get_consolidation_churn_limit(state)) <= \
        int(spec.MIN_ACTIVATION_BALANCE)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_exited_source(spec, state):
    _stage(spec, state)
    make_exited(spec, state, 0)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_exited_target(spec, state):
    _stage(spec, state)
    make_exited(spec, state, 1)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_inactive_source(spec, state):
    _stage(spec, state)
    make_inactive(spec, state, 0)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_inactive_target(spec, state):
    _stage(spec, state)
    make_inactive(spec, state, 1)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_no_source_execution_withdrawal_credential(spec, state):
    # source keeps default 0x00 BLS credentials
    age_past_exit_gate(spec, state)
    set_compounding_withdrawal_credentials(spec, state, 1)
    scale_churn(spec, state)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_target_with_bls_credential(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    scale_churn(spec, state)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_target_with_eth1_credential(spec, state):
    _stage(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 1)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_incorrect_source_address(spec, state):
    _stage(spec, state)
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _request(spec, state, address=WRONG_ADDRESS), mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_unknown_source_pubkey(spec, state):
    _stage(spec, state)
    request = spec.ConsolidationRequest(
        source_address=DEFAULT_ADDRESS,
        source_pubkey=pubkeys[len(state.validators) + 3],
        target_pubkey=state.validators[1].pubkey)
    yield from run_request_processing(
        spec, state, "consolidation_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_unknown_target_pubkey(spec, state):
    _stage(spec, state)
    request = spec.ConsolidationRequest(
        source_address=DEFAULT_ADDRESS,
        source_pubkey=state.validators[0].pubkey,
        target_pubkey=pubkeys[len(state.validators) + 3])
    yield from run_request_processing(
        spec, state, "consolidation_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_source_has_pending_withdrawal(spec, state):
    _stage(spec, state)
    add_pending_partial_withdrawal(spec, state, 0)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_source_not_active_long_enough(spec, state):
    # no aging: activation + SHARD_COMMITTEE_PERIOD gate fails
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    set_compounding_withdrawal_credentials(spec, state, 1)
    scale_churn(spec, state)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state),
        mutates=False)


# ---------------------------------------------------------------------------
# ignored switch-to-compounding
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_switch_to_compounding_exited_source(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    make_exited(spec, state, 0)
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0), mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_switch_to_compounding_inactive_source(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    make_inactive(spec, state, 0)
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0), mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_switch_to_compounding_source_bls_withdrawal_credential(spec, state):
    # 0x00 source credentials: neither a valid switch nor (same-pubkey)
    # a valid consolidation
    age_past_exit_gate(spec, state)
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0), mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_switch_to_compounding_source_coumpounding_withdrawal_credential(spec, state):
    age_past_exit_gate(spec, state)
    set_compounding_withdrawal_credentials(spec, state, 0,
                                           address=DEFAULT_ADDRESS)
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0), mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_switch_to_compounding_not_authorized(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    yield from run_request_processing(
        spec, state, "consolidation_request",
        _switch_request(spec, state, 0, address=WRONG_ADDRESS),
        mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_switch_to_compounding_unknown_source_pubkey(spec, state):
    age_past_exit_gate(spec, state)
    unknown = pubkeys[len(state.validators) + 3]
    request = spec.ConsolidationRequest(
        source_address=DEFAULT_ADDRESS,
        source_pubkey=unknown,
        target_pubkey=unknown)
    yield from run_request_processing(
        spec, state, "consolidation_request", request, mutates=False)


# ---------------------------------------------------------------------------
# consolidation-churn epoch placement (reference round-out)
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_basic_consolidation_in_current_consolidation_epoch(spec, state):
    """Churn already flowing in the CURRENT consolidation epoch with
    room to spare: the new consolidation shares that epoch."""
    _stage(spec, state)
    churn_epoch = spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state))
    state.earliest_consolidation_epoch = churn_epoch
    state.consolidation_balance_to_consume = uint64(
        int(spec.get_consolidation_churn_limit(state)))
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1
    assert int(state.validators[0].exit_epoch) == int(churn_epoch)


@with_all_phases_from("electra")
@spec_state_test
def test_basic_consolidation_in_new_consolidation_epoch(spec, state):
    """No churn flowing yet: the consolidation opens a fresh epoch at
    the activation-exit horizon."""
    _stage(spec, state)
    assert int(state.earliest_consolidation_epoch) <= int(
        spec.get_current_epoch(state))
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1
    assert int(state.validators[0].exit_epoch) == int(
        spec.compute_activation_exit_epoch(
            spec.get_current_epoch(state)))


@with_all_phases_from("electra")
@spec_state_test
def test_basic_consolidation_with_insufficient_preexisting_churn(
        spec, state):
    """Almost no churn left this epoch: the exit spills to the NEXT
    consolidation epoch."""
    _stage(spec, state)
    churn_epoch = spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state))
    state.earliest_consolidation_epoch = churn_epoch
    state.consolidation_balance_to_consume = uint64(1)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1
    assert int(state.validators[0].exit_epoch) > int(churn_epoch)


@with_all_phases_from("electra")
@spec_state_test
def test_consolidation_churn_limit_balance(spec, state):
    """Source balance EXACTLY the churn limit: consumes the whole epoch
    but stays within it."""
    _stage(spec, state)
    # the churn limit moves with total balance as we raise the source's
    # EB — iterate to the fixpoint where balance == churn exactly
    for _ in range(6):
        churn = int(spec.get_consolidation_churn_limit(state))
        state.validators[0].effective_balance = uint64(churn)
        state.balances[0] = uint64(churn)
    assert int(spec.get_consolidation_churn_limit(state)) == \
        int(state.validators[0].effective_balance)
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1
    assert int(state.consolidation_balance_to_consume) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_consolidation_balance_through_two_churn_epochs(spec, state):
    """Source balance worth ~3 epochs of churn: the exit epoch lands
    two epochs past the horizon."""
    _stage(spec, state)
    churn = int(spec.get_consolidation_churn_limit(state))
    state.validators[0].effective_balance = uint64(churn * 3)
    state.balances[0] = uint64(churn * 3)
    horizon = int(spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state)))
    yield from run_request_processing(
        spec, state, "consolidation_request", _request(spec, state))
    assert len(state.pending_consolidations) == 1
    assert int(state.validators[0].exit_epoch) >= horizon + 2
