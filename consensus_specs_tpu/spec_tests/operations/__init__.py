"""Operation-processing spec tests (pre + operation + post vectors)."""

OPERATION_HANDLERS = {
    "attestation": "consensus_specs_tpu.spec_tests.operations.test_attestation",
    "block_header": "consensus_specs_tpu.spec_tests.operations.test_block_header",
    "proposer_slashing":
        "consensus_specs_tpu.spec_tests.operations.test_proposer_slashing",
    "attester_slashing":
        "consensus_specs_tpu.spec_tests.operations.test_attester_slashing",
    "deposit": "consensus_specs_tpu.spec_tests.operations.test_deposit",
    "voluntary_exit":
        "consensus_specs_tpu.spec_tests.operations.test_voluntary_exit",
}
