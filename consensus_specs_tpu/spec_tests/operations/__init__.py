"""Operation-processing spec tests (pre + operation + post vectors)."""

OPERATION_HANDLERS = {
    "attestation": "consensus_specs_tpu.spec_tests.operations.test_attestation",
    "block_header": "consensus_specs_tpu.spec_tests.operations.test_block_header",
    "proposer_slashing":
        "consensus_specs_tpu.spec_tests.operations.test_proposer_slashing",
    "attester_slashing":
        "consensus_specs_tpu.spec_tests.operations.test_attester_slashing",
    "deposit": "consensus_specs_tpu.spec_tests.operations.test_deposit",
    "voluntary_exit":
        "consensus_specs_tpu.spec_tests.operations.test_voluntary_exit",
    "sync_aggregate":
        "consensus_specs_tpu.spec_tests.operations.test_sync_aggregate",
    "withdrawals":
        "consensus_specs_tpu.spec_tests.operations.test_withdrawals",
    "bls_to_execution_change":
        "consensus_specs_tpu.spec_tests.operations."
        "test_bls_to_execution_change",
    "execution_payload":
        "consensus_specs_tpu.spec_tests.operations.test_execution_payload",
    "withdrawal_request":
        "consensus_specs_tpu.spec_tests.operations.test_withdrawal_request",
    "deposit_request":
        "consensus_specs_tpu.spec_tests.operations.test_deposit_request",
    "consolidation_request":
        "consensus_specs_tpu.spec_tests.operations."
        "test_consolidation_request",
}
