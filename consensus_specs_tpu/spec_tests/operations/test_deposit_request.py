"""EIP-6110 EL-triggered deposit request operation tests (electra+).

Reference battery:
test/electra/block_processing/test_process_deposit_request.py (8
cases).  process_deposit_request only queues a PendingDeposit (signature
validity is judged later by process_pending_deposits), so every case
mutates the state.
"""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases_from
from ...test_infra.keys import pubkeys, privkeys
from ...test_infra.deposits import build_deposit_data
from ...test_infra.electra_requests import run_request_processing


def _signed_request(spec, state, validator_index, amount,
                    withdrawal_credentials, index=0, valid_sig=True):
    pubkey = pubkeys[validator_index]
    data = build_deposit_data(
        spec, pubkey, privkeys[validator_index], amount,
        withdrawal_credentials, signed=valid_sig)
    if not valid_sig:
        data.signature = b"\x11" + b"\x00" * 95
    return spec.DepositRequest(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        amount=uint64(amount),
        signature=data.signature,
        index=uint64(index))


def _run(spec, state, request):
    yield from run_request_processing(
        spec, state, "deposit_request", request)


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_min_activation(spec, state):
    fresh = len(state.validators)
    request = _signed_request(
        spec, state, fresh, int(spec.MIN_ACTIVATION_BALANCE),
        b"\x01" + b"\x00" * 31)
    yield from _run(spec, state, request)
    assert len(state.pending_deposits) == 1
    assert state.pending_deposits[0].amount == \
        spec.MIN_ACTIVATION_BALANCE
    assert state.pending_deposits[0].slot == state.slot


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_max_effective_balance_compounding(spec, state):
    fresh = len(state.validators)
    request = _signed_request(
        spec, state, fresh, int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA),
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11
        + b"\xaa" * 20)
    yield from _run(spec, state, request)
    assert int(state.pending_deposits[0].amount) == \
        int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_top_up(spec, state):
    # deposit for an already-registered pubkey queues a top-up
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    request = _signed_request(
        spec, state, 0, amount, b"\x01" + b"\x00" * 31)
    yield from _run(spec, state, request)
    assert len(state.pending_deposits) == 1
    assert int(state.pending_deposits[0].amount) == amount


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_top_up_compounding(spec, state):
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    request = _signed_request(
        spec, state, 0, amount,
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11
        + b"\xaa" * 20)
    yield from _run(spec, state, request)
    assert len(state.pending_deposits) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_invalid_sig(spec, state):
    # still queued — the signature is judged at apply time
    fresh = len(state.validators)
    request = _signed_request(
        spec, state, fresh, int(spec.MIN_ACTIVATION_BALANCE),
        b"\x01" + b"\x00" * 31, valid_sig=False)
    yield from _run(spec, state, request)
    assert len(state.pending_deposits) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_top_up_invalid_sig(spec, state):
    amount = int(spec.MIN_ACTIVATION_BALANCE) // 4
    request = _signed_request(
        spec, state, 0, amount, b"\x01" + b"\x00" * 31,
        valid_sig=False)
    yield from _run(spec, state, request)
    assert len(state.pending_deposits) == 1


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_set_start_index(spec, state):
    fresh = len(state.validators)
    request = _signed_request(
        spec, state, fresh, int(spec.MIN_ACTIVATION_BALANCE),
        b"\x01" + b"\x00" * 31, index=5)
    assert state.deposit_requests_start_index == \
        spec.UNSET_DEPOSIT_REQUESTS_START_INDEX
    yield from _run(spec, state, request)
    assert state.deposit_requests_start_index == uint64(5)


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_set_start_index_only_once(spec, state):
    fresh = len(state.validators)
    first = _signed_request(
        spec, state, fresh, int(spec.MIN_ACTIVATION_BALANCE),
        b"\x01" + b"\x00" * 31, index=5)
    second = _signed_request(
        spec, state, fresh, int(spec.MIN_ACTIVATION_BALANCE),
        b"\x01" + b"\x00" * 31, index=9)
    spec.process_deposit_request(state, first)
    assert state.deposit_requests_start_index == uint64(5)
    yield from _run(spec, state, second)
    assert state.deposit_requests_start_index == uint64(5)
    assert len(state.pending_deposits) == 2
