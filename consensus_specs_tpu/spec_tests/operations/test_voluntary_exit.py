"""process_voluntary_exit operation tests."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases, always_bls)
from ...test_infra.blocks import transition_to
from ...test_infra.slashings import get_valid_voluntary_exit


def _mature_state(spec, state):
    """Exit requires activation + SHARD_COMMITTEE_PERIOD epochs."""
    epochs = int(spec.config.SHARD_COMMITTEE_PERIOD) + 1
    transition_to(spec, state,
                  state.slot + epochs * spec.SLOTS_PER_EPOCH)


def run_voluntary_exit_processing(spec, state, signed_exit, valid=True):
    yield "pre", state.copy()
    yield "voluntary_exit", signed_exit
    index = int(signed_exit.message.validator_index)
    if not valid:
        try:
            spec.process_voluntary_exit(state, signed_exit)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("voluntary exit unexpectedly valid")
    spec.process_voluntary_exit(state, signed_exit)
    assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_exit(spec, state):
    _mature_state(spec, state)
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_exit_signature(spec, state):
    _mature_state(spec, state)
    signed_exit = get_valid_voluntary_exit(spec, state, 0, signed=False)
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_validator_not_active_long_enough(spec, state):
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_exit_in_future(spec, state):
    _mature_state(spec, state)
    exit_msg = spec.VoluntaryExit(
        epoch=uint64(int(spec.get_current_epoch(state)) + 10),
        validator_index=uint64(0))
    from ...test_infra.keys import privkey_for_pubkey
    from ...test_infra.slashings import sign_voluntary_exit
    signed_exit = sign_voluntary_exit(
        spec, state, exit_msg,
        privkey_for_pubkey(state.validators[0].pubkey))
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)
