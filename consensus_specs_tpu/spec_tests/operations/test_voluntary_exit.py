"""process_voluntary_exit operation tests."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases, always_bls)

from ...test_infra.slashings import get_valid_voluntary_exit


def _mature_state(spec, state):
    """Exit requires activation + SHARD_COMMITTEE_PERIOD epochs; jump
    the clock there directly (the reference assigns state.slot the
    same way — processing ~520 empty slots adds nothing the exit path
    reads)."""
    epochs = int(spec.config.SHARD_COMMITTEE_PERIOD) + 1
    state.slot = uint64(int(state.slot)
                        + epochs * int(spec.SLOTS_PER_EPOCH))


def run_voluntary_exit_processing(spec, state, signed_exit, valid=True):
    yield "pre", state.copy()
    yield "voluntary_exit", signed_exit
    index = int(signed_exit.message.validator_index)
    if not valid:
        try:
            spec.process_voluntary_exit(state, signed_exit)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("voluntary exit unexpectedly valid")
    spec.process_voluntary_exit(state, signed_exit)
    assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_exit(spec, state):
    _mature_state(spec, state)
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_exit_signature(spec, state):
    _mature_state(spec, state)
    signed_exit = get_valid_voluntary_exit(spec, state, 0, signed=False)
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_validator_not_active_long_enough(spec, state):
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_exit_in_future(spec, state):
    _mature_state(spec, state)
    exit_msg = spec.VoluntaryExit(
        epoch=uint64(int(spec.get_current_epoch(state)) + 10),
        validator_index=uint64(0))
    from ...test_infra.keys import privkey_for_pubkey
    from ...test_infra.slashings import sign_voluntary_exit
    signed_exit = sign_voluntary_exit(
        spec, state, exit_msg,
        privkey_for_pubkey(state.validators[0].pubkey))
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


from ...test_infra.context import (  # noqa: E402
    with_pytest_fork_subset)


def _teleport_mature(spec, state):

    """Jump the clock past the exit-eligibility gate (cheap: no slot
    processing, like the reference's direct slot assignment)."""
    state.slot = uint64(
        (int(spec.config.SHARD_COMMITTEE_PERIOD) + 1)
        * int(spec.SLOTS_PER_EPOCH))


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_success_exit_queue_min_churn(spec, state):
    """Fill one churn-limit worth of exits; all land on the same exit
    epoch."""
    _teleport_mature(spec, state)
    churn = int(spec.get_validator_churn_limit(state)) \
        if not spec.is_post("electra") else 2
    exits = [get_valid_voluntary_exit(spec, state, i)
             for i in range(churn)]
    yield "pre", state.copy()
    for ve in exits:
        spec.process_voluntary_exit(state, ve)
    epochs = {int(state.validators[i].exit_epoch)
              for i in range(churn)}
    if not spec.is_post("electra"):
        assert len(epochs) == 1
    yield "voluntary_exit", exits[0]
    yield "post", state


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_default_exit_epoch_subsequent_exit(spec, state):
    """A second exit in the same epoch lands at (or after) the first's
    exit epoch."""
    _teleport_mature(spec, state)
    first = get_valid_voluntary_exit(spec, state, 0)
    second = get_valid_voluntary_exit(spec, state, 1)
    yield "pre", state.copy()
    spec.process_voluntary_exit(state, first)
    spec.process_voluntary_exit(state, second)
    assert int(state.validators[1].exit_epoch) >= \
        int(state.validators[0].exit_epoch)
    yield "voluntary_exit", second
    yield "post", state


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_validator_exit_in_future(spec, state):
    _teleport_mature(spec, state)
    exit_msg = spec.VoluntaryExit(
        epoch=uint64(int(spec.get_current_epoch(state)) + 1),
        validator_index=uint64(0))
    from ...test_infra.keys import privkey_for_pubkey
    from ...test_infra.slashings import sign_voluntary_exit
    signed = sign_voluntary_exit(
        spec, state, exit_msg,
        privkey_for_pubkey(state.validators[0].pubkey))
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_incorrect_validator_index(spec, state):
    _teleport_mature(spec, state)
    signed = get_valid_voluntary_exit(spec, state, 0)
    signed.message.validator_index = uint64(len(state.validators))
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_validator_not_active(spec, state):
    _teleport_mature(spec, state)
    cur = int(spec.get_current_epoch(state))
    state.validators[0].exit_epoch = uint64(max(cur - 1, 0))
    signed = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_validator_already_exited(spec, state):
    _teleport_mature(spec, state)
    state.validators[0].exit_epoch = uint64(
        int(spec.get_current_epoch(state)) + 5)
    signed = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)
