"""process_voluntary_exit operation tests."""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases, with_all_phases_from, always_bls)

from ...test_infra.slashings import get_valid_voluntary_exit


def _mature_state(spec, state):
    """Exit requires activation + SHARD_COMMITTEE_PERIOD epochs; jump
    the clock there directly (the reference assigns state.slot the
    same way — processing ~520 empty slots adds nothing the exit path
    reads)."""
    epochs = int(spec.config.SHARD_COMMITTEE_PERIOD) + 1
    state.slot = uint64(int(state.slot)
                        + epochs * int(spec.SLOTS_PER_EPOCH))


def run_voluntary_exit_processing(spec, state, signed_exit, valid=True):
    yield "pre", state.copy()
    yield "voluntary_exit", signed_exit
    index = int(signed_exit.message.validator_index)
    if not valid:
        try:
            spec.process_voluntary_exit(state, signed_exit)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("voluntary exit unexpectedly valid")
    spec.process_voluntary_exit(state, signed_exit)
    assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_exit(spec, state):
    _mature_state(spec, state)
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)


@with_all_phases
@spec_state_test
@always_bls
def test_invalid_exit_signature(spec, state):
    _mature_state(spec, state)
    signed_exit = get_valid_voluntary_exit(spec, state, 0, signed=False)
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_validator_not_active_long_enough(spec, state):
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases
@spec_state_test
def test_invalid_exit_in_future(spec, state):
    _mature_state(spec, state)
    exit_msg = spec.VoluntaryExit(
        epoch=uint64(int(spec.get_current_epoch(state)) + 10),
        validator_index=uint64(0))
    from ...test_infra.keys import privkey_for_pubkey
    from ...test_infra.slashings import sign_voluntary_exit
    signed_exit = sign_voluntary_exit(
        spec, state, exit_msg,
        privkey_for_pubkey(state.validators[0].pubkey))
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


from ...test_infra.context import (  # noqa: E402
    with_pytest_fork_subset)


def _teleport_mature(spec, state):

    """Jump the clock past the exit-eligibility gate (cheap: no slot
    processing, like the reference's direct slot assignment)."""
    state.slot = uint64(
        (int(spec.config.SHARD_COMMITTEE_PERIOD) + 1)
        * int(spec.SLOTS_PER_EPOCH))


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_success_exit_queue_min_churn(spec, state):
    """Fill one churn-limit worth of exits; all land on the same exit
    epoch."""
    _teleport_mature(spec, state)
    churn = int(spec.get_validator_churn_limit(state)) \
        if not spec.is_post("electra") else 2
    exits = [get_valid_voluntary_exit(spec, state, i)
             for i in range(churn)]
    yield "pre", state.copy()
    for ve in exits:
        spec.process_voluntary_exit(state, ve)
    epochs = {int(state.validators[i].exit_epoch)
              for i in range(churn)}
    if not spec.is_post("electra"):
        assert len(epochs) == 1
    yield "voluntary_exit", exits[0]
    yield "post", state


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_default_exit_epoch_subsequent_exit(spec, state):
    """A second exit in the same epoch lands at (or after) the first's
    exit epoch."""
    _teleport_mature(spec, state)
    first = get_valid_voluntary_exit(spec, state, 0)
    second = get_valid_voluntary_exit(spec, state, 1)
    yield "pre", state.copy()
    spec.process_voluntary_exit(state, first)
    spec.process_voluntary_exit(state, second)
    assert int(state.validators[1].exit_epoch) >= \
        int(state.validators[0].exit_epoch)
    yield "voluntary_exit", second
    yield "post", state


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_validator_exit_in_future(spec, state):
    _teleport_mature(spec, state)
    exit_msg = spec.VoluntaryExit(
        epoch=uint64(int(spec.get_current_epoch(state)) + 1),
        validator_index=uint64(0))
    from ...test_infra.keys import privkey_for_pubkey
    from ...test_infra.slashings import sign_voluntary_exit
    signed = sign_voluntary_exit(
        spec, state, exit_msg,
        privkey_for_pubkey(state.validators[0].pubkey))
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_incorrect_validator_index(spec, state):
    _teleport_mature(spec, state)
    signed = get_valid_voluntary_exit(spec, state, 0)
    signed.message.validator_index = uint64(len(state.validators))
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_validator_not_active(spec, state):
    _teleport_mature(spec, state)
    cur = int(spec.get_current_epoch(state))
    state.validators[0].exit_epoch = uint64(max(cur - 1, 0))
    signed = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@with_all_phases
@with_pytest_fork_subset(["phase0", "deneb", "electra"])
@spec_state_test
def test_invalid_validator_already_exited(spec, state):
    _teleport_mature(spec, state)
    state.validators[0].exit_epoch = uint64(
        int(spec.get_current_epoch(state)) + 5)
    signed = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


# ---------------------------------------------------------------------------
# fork-version signing matrix (EIP-7044; reference deneb
# test_process_voluntary_exit.py fork-version battery)
# ---------------------------------------------------------------------------

def _signed_exit_with_version(spec, state, validator_index, version):
    from ...test_infra.keys import privkey_for_pubkey
    from ...utils import bls
    voluntary_exit = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state),
        validator_index=uint64(validator_index))
    domain = spec.compute_domain(
        spec.DOMAIN_VOLUNTARY_EXIT,
        version, state.genesis_validators_root)
    signing_root = spec.compute_signing_root(voluntary_exit, domain)
    privkey = privkey_for_pubkey(state.validators[validator_index].pubkey)
    return spec.SignedVoluntaryExit(
        message=voluntary_exit, signature=bls.Sign(privkey, signing_root))


def _version_bytes(spec, name):
    return bytes.fromhex(str(getattr(spec.config, name))[2:])


@with_all_phases_from("deneb")
@spec_state_test
@always_bls
def test_voluntary_exit_with_pinned_capella_fork_version(spec, state):
    """EIP-7044: post-deneb exits sign over the CAPELLA fork domain
    regardless of the exit epoch's fork."""
    _mature_state(spec, state)
    signed_exit = _signed_exit_with_version(
        spec, state, 0, _version_bytes(spec, "CAPELLA_FORK_VERSION"))
    yield from run_voluntary_exit_processing(spec, state, signed_exit)


@with_all_phases_from("deneb")
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_with_current_fork_version(spec, state):
    """Post-deneb, signing over the CURRENT fork version must fail —
    only the pinned capella domain verifies."""
    _mature_state(spec, state)
    signed_exit = _signed_exit_with_version(
        spec, state, 0,
        _version_bytes(spec, f"{spec.fork.upper()}_FORK_VERSION"))
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


@with_all_phases_from("deneb")
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_with_genesis_fork_version(spec, state):
    _mature_state(spec, state)
    signed_exit = _signed_exit_with_version(
        spec, state, 0, _version_bytes(spec, "GENESIS_FORK_VERSION"))
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)


# ---------------------------------------------------------------------------
# electra exit churn (EIP-7251; reference electra voluntary-exit battery)
# ---------------------------------------------------------------------------

def _prepare_exit_balance(spec, state, validator_index, balance):
    from ...test_infra.withdrawals import (
        set_compounding_withdrawal_credentials)
    set_compounding_withdrawal_credentials(spec, state, validator_index)
    state.validators[validator_index].effective_balance = uint64(balance)
    state.balances[validator_index] = uint64(balance)


@with_all_phases_from("electra")
@spec_state_test
def test_exit_with_balance_equal_to_churn_limit(spec, state):
    _mature_state(spec, state)
    # raising the validator's EB raises total balance and with it the
    # churn limit — iterate to a fixpoint so balance == churn exactly
    for _ in range(4):
        churn_limit = int(spec.get_activation_exit_churn_limit(state))
        _prepare_exit_balance(spec, state, 0, churn_limit)
    assert int(spec.get_activation_exit_churn_limit(state)) \
        == int(state.validators[0].effective_balance)
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)
    # consumed exactly one epoch's churn
    assert int(state.validators[0].exit_epoch) == int(
        spec.compute_activation_exit_epoch(spec.get_current_epoch(state)))
    assert int(state.exit_balance_to_consume) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_exit_with_balance_multiple_of_churn_limit(spec, state):
    _mature_state(spec, state)
    mult = 2
    for _ in range(4):
        churn_limit = int(spec.get_activation_exit_churn_limit(state))
        _prepare_exit_balance(spec, state, 0, churn_limit * mult)
    assert int(spec.get_activation_exit_churn_limit(state)) * mult \
        == int(state.validators[0].effective_balance)
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)
    # the exit needs `mult` epochs of churn
    assert int(state.validators[0].exit_epoch) == int(
        spec.compute_activation_exit_epoch(
            spec.get_current_epoch(state))) + mult - 1
    assert int(state.exit_balance_to_consume) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_exit_existing_churn_and_churn_limit_balance(spec, state):
    _mature_state(spec, state)
    churn_limit = int(spec.get_activation_exit_churn_limit(state))
    existing = churn_limit // 2
    # pre-consume half the current epoch's churn
    state.earliest_exit_epoch = spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state))
    state.exit_balance_to_consume = uint64(churn_limit - existing)
    _prepare_exit_balance(spec, state, 0, churn_limit)
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)
    # the new exit overflows into the next churn epoch
    assert int(state.validators[0].exit_epoch) == int(
        spec.compute_activation_exit_epoch(
            spec.get_current_epoch(state))) + 1


@with_all_phases_from("electra")
@spec_state_test
def test_min_balance_exit(spec, state):
    _mature_state(spec, state)
    churn_limit = int(spec.get_activation_exit_churn_limit(state))
    _prepare_exit_balance(spec, state, 0,
                          int(spec.MIN_ACTIVATION_BALANCE))
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)
    assert int(state.exit_balance_to_consume) == \
        churn_limit - int(spec.MIN_ACTIVATION_BALANCE)


@with_all_phases_from("electra")
@spec_state_test
def test_min_balance_exits_up_to_churn(spec, state):
    """Several min-balance exits inside one epoch's churn all land in
    the same exit epoch."""
    _mature_state(spec, state)
    churn_limit = int(spec.get_activation_exit_churn_limit(state))
    n = churn_limit // int(spec.MIN_ACTIVATION_BALANCE)
    expected_epoch = spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state))
    for i in range(n):
        _prepare_exit_balance(spec, state, i,
                              int(spec.MIN_ACTIVATION_BALANCE))
        signed_exit = get_valid_voluntary_exit(spec, state, i)
        if i == n - 1:
            yield from run_voluntary_exit_processing(spec, state,
                                                     signed_exit)
        else:
            spec.process_voluntary_exit(state, signed_exit)
        assert int(state.validators[i].exit_epoch) == int(expected_epoch)


@with_all_phases_from("electra")
@spec_state_test
def test_min_balance_exits_above_churn(spec, state):
    """One exit beyond the epoch's churn spills to the next epoch."""
    _mature_state(spec, state)
    churn_limit = int(spec.get_activation_exit_churn_limit(state))
    n = churn_limit // int(spec.MIN_ACTIVATION_BALANCE)
    expected_epoch = spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state))
    for i in range(n):
        _prepare_exit_balance(spec, state, i,
                              int(spec.MIN_ACTIVATION_BALANCE))
        spec.process_voluntary_exit(
            state, get_valid_voluntary_exit(spec, state, i))
    _prepare_exit_balance(spec, state, n,
                          int(spec.MIN_ACTIVATION_BALANCE))
    signed_exit = get_valid_voluntary_exit(spec, state, n)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)
    assert int(state.validators[n].exit_epoch) == int(expected_epoch) + 1


@with_all_phases_from("electra")
@spec_state_test
def test_max_balance_exit(spec, state):
    _mature_state(spec, state)
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    _prepare_exit_balance(spec, state, 0, max_eb)
    # churn evaluated AFTER the balance bump (it feeds total balance)
    churn_limit = int(spec.get_activation_exit_churn_limit(state))
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit)
    # exit spans ceil(max_eb / churn) epochs of churn
    earliest = int(spec.compute_activation_exit_epoch(
        spec.get_current_epoch(state)))
    additional = (max_eb - churn_limit + churn_limit - 1) // churn_limit
    assert int(state.validators[0].exit_epoch) == earliest + additional


@with_all_phases_from("electra")
@spec_state_test
def test_invalid_validator_has_pending_withdrawal(spec, state):
    from ...test_infra.withdrawals import prepare_pending_withdrawal
    _mature_state(spec, state)
    prepare_pending_withdrawal(spec, state, 0)
    signed_exit = get_valid_voluntary_exit(spec, state, 0)
    yield from run_voluntary_exit_processing(spec, state, signed_exit,
                                             valid=False)
