"""Electra EL-triggered request operation tests: withdrawal requests
(EIP-7002), deposit requests (EIP-6110), consolidation requests
(EIP-7251).  Reference shapes:
test/electra/block_processing/test_process_{withdrawal,deposit,consolidation}_request.py.

Request processing is no-fault: malformed requests are ignored, not
rejected, so "invalid" cases assert the state is untouched."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases_from
from ...test_infra.keys import pubkeys
from ...test_infra.withdrawals import (
    set_eth1_withdrawal_credentials,
    set_compounding_withdrawal_credentials)

_ADDR = b"\xaa" * 20


def _run(spec, state, kind, request, mutates=True):
    pre = state.copy()
    yield "pre", pre
    yield kind, request
    getattr(spec, f"process_{kind}")(state, request)
    if not mutates:
        assert spec.hash_tree_root(state) == spec.hash_tree_root(pre)
    yield "post", state


def _age_validator(spec, state, index):
    """Move the chain past the shard-committee-period gate for exits."""
    state.slot = uint64(
        int(state.slot)
        + int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH))


# ---------------------------------------------------------------------------
# withdrawal requests (EIP-7002)
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_withdrawal_request_full_exit(spec, state):
    _age_validator(spec, state, 0)
    set_eth1_withdrawal_credentials(spec, state, 0, address=_ADDR)
    request = spec.WithdrawalRequest(
        source_address=_ADDR,
        validator_pubkey=state.validators[0].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT)
    yield from _run(spec, state, "withdrawal_request", request)
    assert state.validators[0].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_withdrawal_request_partial(spec, state):
    _age_validator(spec, state, 0)
    set_compounding_withdrawal_credentials(spec, state, 0, address=_ADDR)
    state.validators[0].effective_balance = spec.MIN_ACTIVATION_BALANCE
    excess = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.balances[0] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE) + excess)
    request = spec.WithdrawalRequest(
        source_address=_ADDR,
        validator_pubkey=state.validators[0].pubkey,
        amount=uint64(excess))
    yield from _run(spec, state, "withdrawal_request", request)
    assert len(state.pending_partial_withdrawals) == 1
    assert int(state.pending_partial_withdrawals[0].amount) == excess


@with_all_phases_from("electra")
@spec_state_test
def test_withdrawal_request_wrong_source_ignored(spec, state):
    _age_validator(spec, state, 0)
    set_eth1_withdrawal_credentials(spec, state, 0, address=_ADDR)
    request = spec.WithdrawalRequest(
        source_address=b"\xbb" * 20,
        validator_pubkey=state.validators[0].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT)
    yield from _run(spec, state, "withdrawal_request", request,
                    mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_withdrawal_request_unknown_pubkey_ignored(spec, state):
    _age_validator(spec, state, 0)
    request = spec.WithdrawalRequest(
        source_address=_ADDR,
        validator_pubkey=pubkeys[len(state.validators) + 7],
        amount=spec.FULL_EXIT_REQUEST_AMOUNT)
    yield from _run(spec, state, "withdrawal_request", request,
                    mutates=False)


# ---------------------------------------------------------------------------
# deposit requests (EIP-6110)
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_appends_pending(spec, state):
    request = spec.DepositRequest(
        pubkey=pubkeys[1],
        withdrawal_credentials=b"\x01" + b"\x00" * 31,
        amount=spec.MIN_ACTIVATION_BALANCE,
        signature=b"\x11" + b"\x00" * 95,
        index=uint64(0))
    yield from _run(spec, state, "deposit_request", request)
    assert len(state.pending_deposits) == 1
    assert state.deposit_requests_start_index == uint64(0)


@with_all_phases_from("electra")
@spec_state_test
def test_deposit_request_start_index_set_once(spec, state):
    for idx in (5, 9):
        request = spec.DepositRequest(
            pubkey=pubkeys[1],
            withdrawal_credentials=b"\x01" + b"\x00" * 31,
            amount=spec.MIN_ACTIVATION_BALANCE,
            signature=b"\x11" + b"\x00" * 95,
            index=uint64(idx))
        if idx == 5:
            yield from _run(spec, state, "deposit_request", request)
        else:
            spec.process_deposit_request(state, request)
    assert state.deposit_requests_start_index == uint64(5)
    assert len(state.pending_deposits) == 2


# ---------------------------------------------------------------------------
# consolidation requests (EIP-7251)
# ---------------------------------------------------------------------------

def _stage_consolidation(spec, state, source=0, target=1):
    _age_validator(spec, state, source)
    set_eth1_withdrawal_credentials(spec, state, source, address=_ADDR)
    set_compounding_withdrawal_credentials(spec, state, target)
    # consolidation churn must exceed MIN_ACTIVATION_BALANCE
    state.balances = [uint64(int(b) * 64) for b in state.balances]
    for v in state.validators:
        v.effective_balance = uint64(int(v.effective_balance) * 64)


@with_all_phases_from("electra")
@spec_state_test
def test_consolidation_request_queues_pending(spec, state):
    _stage_consolidation(spec, state)
    request = spec.ConsolidationRequest(
        source_address=_ADDR,
        source_pubkey=state.validators[0].pubkey,
        target_pubkey=state.validators[1].pubkey)
    yield from _run(spec, state, "consolidation_request", request)
    assert len(state.pending_consolidations) == 1
    assert state.validators[0].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_consolidation_request_switch_to_compounding(spec, state):
    _age_validator(spec, state, 0)
    set_eth1_withdrawal_credentials(spec, state, 0, address=_ADDR)
    request = spec.ConsolidationRequest(
        source_address=_ADDR,
        source_pubkey=state.validators[0].pubkey,
        target_pubkey=state.validators[0].pubkey)
    yield from _run(spec, state, "consolidation_request", request)
    creds = bytes(state.validators[0].withdrawal_credentials)
    assert creds[:1] == bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX)


@with_all_phases_from("electra")
@spec_state_test
def test_consolidation_request_unknown_target_ignored(spec, state):
    _stage_consolidation(spec, state)
    request = spec.ConsolidationRequest(
        source_address=_ADDR,
        source_pubkey=state.validators[0].pubkey,
        target_pubkey=pubkeys[len(state.validators) + 3])
    yield from _run(spec, state, "consolidation_request", request,
                    mutates=False)
