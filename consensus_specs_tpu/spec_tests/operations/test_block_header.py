"""process_block_header operation tests."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases
from ...test_infra.blocks import build_empty_block_for_next_slot


def run_block_header_processing(spec, state, block, valid=True):
    if int(state.slot) < int(block.slot):
        spec.process_slots(state, block.slot)
    yield "pre", state.copy()
    yield "block", block
    if not valid:
        try:
            spec.process_block_header(state, block)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("block header unexpectedly valid")
    spec.process_block_header(state, block)
    yield "post", state


@with_all_phases
@spec_state_test
def test_basic_block_header(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    yield from run_block_header_processing(spec, state, block)


@with_all_phases
@spec_state_test
def test_invalid_slot_block_header(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    block.slot = uint64(int(state.slot) + 2)   # header slot != state slot
    yield "pre", state.copy()
    yield "block", block
    try:
        spec.process_block_header(state, block)
    except AssertionError:
        yield "post", None
        return
    raise AssertionError("unexpectedly valid")


@with_all_phases
@spec_state_test
def test_invalid_parent_root(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    block.parent_root = b"\x99" * 32
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_index(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    block.proposer_index = uint64(
        (int(block.proposer_index) + 1) % len(state.validators))
    yield from run_block_header_processing(spec, state, block, valid=False)


@with_all_phases
@spec_state_test
def test_invalid_proposer_slashed(spec, state):
    """A slashed proposer may not propose."""
    block = build_empty_block_for_next_slot(spec, state)
    state.validators[int(block.proposer_index)].slashed = True
    yield from run_block_header_processing(spec, state, block,
                                           valid=False)


@with_all_phases
@spec_state_test
def test_invalid_multiple_blocks_single_slot(spec, state):
    """A second header at an already-headed slot must be rejected."""
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    spec.process_block_header(state, block)
    second = block.copy()
    second.body.graffiti = b"\x22" * 32
    yield from run_block_header_processing(spec, state, second,
                                           valid=False)
