"""process_bls_to_execution_change operation tests (capella+;
reference: test/capella/block_processing/test_process_bls_to_execution_change.py
shape)."""
from ...ssz import Bytes32, uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, always_bls)
from ...test_infra.keys import privkeys, pubkeys
from ...utils import bls


def _stage_bls_credentials(spec, state, index, key_index=None):
    """Give validator `index` 0x00 BLS credentials derived from a test
    key we control; returns the (pubkey, privkey) pair used."""
    key_index = index if key_index is None else key_index
    from_pubkey = pubkeys[key_index]
    creds = bytes(spec.BLS_WITHDRAWAL_PREFIX) + \
        bytes(spec.hash(from_pubkey))[1:]
    state.validators[index].withdrawal_credentials = Bytes32(creds)
    return from_pubkey, privkeys[key_index]


def _signed_change(spec, state, index, from_pubkey, privkey,
                   address=b"\x42" * 20, sign=True):
    change = spec.BLSToExecutionChange(
        validator_index=uint64(index),
        from_bls_pubkey=from_pubkey,
        to_execution_address=address)
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        genesis_validators_root=state.genesis_validators_root)
    signature = bls.Sign(privkey, spec.compute_signing_root(
        change, domain)) if sign else b"\x11" + b"\x00" * 95
    return spec.SignedBLSToExecutionChange(message=change,
                                           signature=signature)


def _run(spec, state, signed_change, valid=True):
    yield "pre", state.copy()
    yield "address_change", signed_change
    if not valid:
        try:
            spec.process_bls_to_execution_change(state, signed_change)
        except (AssertionError, ValueError, IndexError):
            yield "post", None
            return
        raise AssertionError("address change unexpectedly valid")
    spec.process_bls_to_execution_change(state, signed_change)
    yield "post", state


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_success(spec, state):
    pub, priv = _stage_bls_credentials(spec, state, 0)
    signed = _signed_change(spec, state, 0, pub, priv)
    yield from _run(spec, state, signed)
    creds = bytes(state.validators[0].withdrawal_credentials)
    assert creds[:1] == bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    assert creds[12:] == b"\x42" * 20


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_invalid_signature(spec, state):
    pub, priv = _stage_bls_credentials(spec, state, 0)
    signed = _signed_change(spec, state, 0, pub, priv, sign=False)
    yield from _run(spec, state, signed, valid=False)


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_invalid_already_execution_credentials(spec, state):
    """Default genesis credentials here are 0x01 — change must fail."""
    pub, priv = _stage_bls_credentials(spec, state, 0)
    state.validators[0].withdrawal_credentials = Bytes32(
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 +
        b"\xaa" * 20)
    signed = _signed_change(spec, state, 0, pub, priv)
    yield from _run(spec, state, signed, valid=False)


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_invalid_wrong_from_pubkey(spec, state):
    """Credentials derived from a different key than the one in the
    change message."""
    _stage_bls_credentials(spec, state, 0, key_index=0)
    wrong_pub, wrong_priv = pubkeys[5], privkeys[5]
    signed = _signed_change(spec, state, 0, wrong_pub, wrong_priv)
    yield from _run(spec, state, signed, valid=False)


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_invalid_validator_index_out_of_range(spec, state):
    pub, priv = _stage_bls_credentials(spec, state, 0)
    signed = _signed_change(spec, state, 0, pub, priv)
    signed.message.validator_index = uint64(len(state.validators))
    yield from _run(spec, state, signed, valid=False)


# ---------------------------------------------------------------------------
# validator-status long tail: the change is status-independent
# ---------------------------------------------------------------------------

@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_success_not_activated(spec, state):
    index = 3
    pub, priv = _stage_bls_credentials(spec, state, index)
    validator = state.validators[index]
    validator.activation_eligibility_epoch = spec.FAR_FUTURE_EPOCH
    validator.activation_epoch = spec.FAR_FUTURE_EPOCH
    yield from _run(spec, state,
                    _signed_change(spec, state, index, pub, priv))
    assert not spec.is_active_validator(
        state.validators[index], spec.get_current_epoch(state))


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_success_in_activation_queue(spec, state):
    index = 3
    pub, priv = _stage_bls_credentials(spec, state, index)
    validator = state.validators[index]
    validator.activation_eligibility_epoch = spec.get_current_epoch(state)
    validator.activation_epoch = uint64(
        int(spec.get_current_epoch(state)) + 3)
    yield from _run(spec, state,
                    _signed_change(spec, state, index, pub, priv))


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_success_in_exit_queue(spec, state):
    index = 3
    pub, priv = _stage_bls_credentials(spec, state, index)
    spec.initiate_validator_exit(state, index)
    assert spec.is_active_validator(
        state.validators[index], spec.get_current_epoch(state))
    yield from _run(spec, state,
                    _signed_change(spec, state, index, pub, priv))


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_success_exited(spec, state):
    index = 4
    pub, priv = _stage_bls_credentials(spec, state, index)
    state.validators[index].exit_epoch = spec.get_current_epoch(state)
    yield from _run(spec, state,
                    _signed_change(spec, state, index, pub, priv))


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_success_withdrawable(spec, state):
    index = 4
    pub, priv = _stage_bls_credentials(spec, state, index)
    state.validators[index].exit_epoch = spec.get_current_epoch(state)
    state.validators[index].withdrawable_epoch = \
        spec.get_current_epoch(state)
    yield from _run(spec, state,
                    _signed_change(spec, state, index, pub, priv))


# ---------------------------------------------------------------------------
# signing-domain matrix: the change domain pins the GENESIS fork version
# ---------------------------------------------------------------------------

def _signed_change_with_version(spec, state, index, from_pubkey, privkey,
                                version, genesis_validators_root=None):
    if genesis_validators_root is None:
        genesis_validators_root = state.genesis_validators_root
    change = spec.BLSToExecutionChange(
        validator_index=uint64(index),
        from_bls_pubkey=from_pubkey,
        to_execution_address=b"\x42" * 20)
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE, version,
        genesis_validators_root)
    signature = bls.Sign(privkey,
                         spec.compute_signing_root(change, domain))
    return spec.SignedBLSToExecutionChange(message=change,
                                           signature=signature)


def _fork_version(spec, name):
    return bytes.fromhex(str(getattr(spec.config, name))[2:])


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_genesis_fork_version(spec, state):
    """The domain uses GENESIS_FORK_VERSION regardless of the current
    fork (capella/beacon-chain.md process_bls_to_execution_change)."""
    pub, priv = _stage_bls_credentials(spec, state, 0)
    signed_change = _signed_change_with_version(
        spec, state, 0, pub, priv,
        _fork_version(spec, "GENESIS_FORK_VERSION"))
    yield from _run(spec, state, signed_change)


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_invalid_current_fork_version(spec, state):
    pub, priv = _stage_bls_credentials(spec, state, 0)
    signed_change = _signed_change_with_version(
        spec, state, 0, pub, priv,
        _fork_version(spec, f"{spec.fork.upper()}_FORK_VERSION"))
    yield from _run(spec, state, signed_change, valid=False)


@with_all_phases_from("capella")
@spec_state_test
@always_bls
def test_invalid_genesis_validators_root(spec, state):
    pub, priv = _stage_bls_credentials(spec, state, 0)
    signed_change = _signed_change_with_version(
        spec, state, 0, pub, priv,
        _fork_version(spec, "GENESIS_FORK_VERSION"),
        genesis_validators_root=b"\x99" * 32)
    yield from _run(spec, state, signed_change, valid=False)
