"""process_withdrawals operation tests (capella+; reference:
test/capella/block_processing/test_process_withdrawals.py shape)."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases_from
from ...test_infra.withdrawals import (
    get_expected_withdrawals, payload_with_expected_withdrawals,
    prepare_fully_withdrawable_validator,
    prepare_partially_withdrawable_validator, run_withdrawals_processing)


@with_all_phases_from("capella")
@spec_state_test
def test_no_withdrawals(spec, state):
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_one_full_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    assert payload.withdrawals[0].amount == state.balances[0]
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[0] == 0
    assert state.next_withdrawal_index == uint64(1)


@with_all_phases_from("capella")
@spec_state_test
def test_one_partial_withdrawal(spec, state):
    excess = 2000000000
    prepare_partially_withdrawable_validator(spec, state, 1,
                                             excess=excess)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    assert int(payload.withdrawals[0].amount) == excess
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[1] == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases_from("capella")
@spec_state_test
def test_mixed_full_and_partial(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    prepare_partially_withdrawable_validator(spec, state, 2)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 2
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_missing_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals = []
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_wrong_amount(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].amount = uint64(
        int(payload.withdrawals[0].amount) + 1)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_wrong_validator_index(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].validator_index = uint64(3)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_sweep_cursor_advances(spec, state):
    """The sweep cursor moves by the bound when the payload isn't
    full."""
    pre_cursor = int(state.next_withdrawal_validator_index)
    payload = payload_with_expected_withdrawals(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    bound = min(len(state.validators),
                int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP))
    assert int(state.next_withdrawal_validator_index) == \
        (pre_cursor + bound) % len(state.validators)


@with_all_phases_from("electra")
@spec_state_test
def test_pending_partial_withdrawal(spec, state):
    """Electra: a pending partial withdrawal request is honored by the
    sweep once withdrawable."""
    from ...test_infra.withdrawals import set_eth1_withdrawal_credentials
    index = 0
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    set_eth1_withdrawal_credentials(spec, state, index)
    state.balances[index] = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE) + int(amount))
    state.pending_partial_withdrawals = [spec.PendingPartialWithdrawal(
        validator_index=index, amount=amount,
        withdrawable_epoch=spec.get_current_epoch(state))]
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) >= 1
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0


from ...test_infra.withdrawals import (  # noqa: E402
    set_eth1_withdrawal_credentials)


@with_all_phases_from("capella")
@spec_state_test
def test_all_fully_withdrawable_in_sweep_window(spec, state):
    """Every validator in the sweep window fully withdrawable: payload
    carries the per-payload cap."""
    bound = min(int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP),
                len(state.validators),
                int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 4)
    for i in range(bound):
        prepare_fully_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == min(
        bound, int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_max_partial_withdrawals_in_one_payload(spec, state):
    cap = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    for i in range(cap + 2):
        prepare_partially_withdrawable_validator(
            spec, state, i % len(state.validators), excess=10**6)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == cap
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_withdrawable_epoch_but_0_balance(spec, state):
    """Fully withdrawable with zero balance: skipped by the sweep."""
    prepare_fully_withdrawable_validator(spec, state, 0, balance=0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != 0
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_withdrawable_epoch_but_0_effective_balance_not_0_balance(
        spec, state):
    """Zero EFFECTIVE balance with real balance: fully withdrawable
    (the sweep keys on withdrawable_epoch + balance)."""
    index = 0
    prepare_fully_withdrawable_validator(spec, state, index)
    state.validators[index].effective_balance = uint64(0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert any(int(w.validator_index) == index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_no_withdrawals_but_some_next_epoch(spec, state):
    """Withdrawability starting next epoch: nothing withdrawable yet."""
    index = 0
    prepare_fully_withdrawable_validator(spec, state, index)
    state.validators[index].withdrawable_epoch = uint64(
        int(spec.get_current_epoch(state)) + 1)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_partially_withdrawable_exact_max_balance(spec, state):
    """Balance exactly AT the max effective balance: NOT partially
    withdrawable (strict inequality)."""
    index = 0
    set_eth1_withdrawal_credentials(spec, state, index)
    state.validators[index].effective_balance = \
        spec.MAX_EFFECTIVE_BALANCE
    state.balances[index] = spec.MAX_EFFECTIVE_BALANCE
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_bls_credentials_not_withdrawable(spec, state):
    """0x00-credentialed validators never enter the sweep, however
    ripe."""
    index = 0
    v = state.validators[index]
    epoch = spec.get_current_epoch(state)
    v.exit_epoch = uint64(max(int(epoch) - 1, 0))
    v.withdrawable_epoch = epoch
    assert bytes(v.withdrawal_credentials)[:1] == \
        bytes(spec.BLS_WITHDRAWAL_PREFIX)
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_withdrawal_index_gap(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) > 0
    payload.withdrawals[0].index = uint64(
        int(payload.withdrawals[0].index) + 1)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_extra_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    extra = payload.withdrawals[0].copy()
    extra.index = uint64(int(extra.index) + 1)
    extra.validator_index = uint64(1)
    payload.withdrawals = list(payload.withdrawals) + [extra]
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_address_mismatch(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) > 0
    payload.withdrawals[0].address = b"\xde" * 20
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_empty_when_expected(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) > 0
    payload.withdrawals = []
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("electra")
@spec_state_test
def test_electra_pending_partial_before_sweep(spec, state):
    """EIP-7251 pending partial withdrawals drain before the sweep and
    consume the per-payload partial budget."""
    from ...test_infra.withdrawals import (
        set_compounding_withdrawal_credentials)
    index = 0
    set_compounding_withdrawal_credentials(spec, state, index)
    state.validators[index].effective_balance = \
        spec.MIN_ACTIVATION_BALANCE
    state.balances[index] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE) + 3 * 10**9)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=uint64(index), amount=uint64(10**9),
            withdrawable_epoch=spec.get_current_epoch(state)))
    payload = payload_with_expected_withdrawals(spec, state)
    assert any(int(w.validator_index) == index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("electra")
@spec_state_test
def test_electra_pending_partial_not_ripe(spec, state):
    """A pending partial whose withdrawable_epoch is in the future
    stays queued."""
    from ...test_infra.withdrawals import (
        set_compounding_withdrawal_credentials)
    index = 0
    set_compounding_withdrawal_credentials(spec, state, index)
    state.validators[index].effective_balance = \
        spec.MIN_ACTIVATION_BALANCE
    state.balances[index] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE) + 3 * 10**9)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=uint64(index), amount=uint64(10**9),
            withdrawable_epoch=uint64(
                int(spec.get_current_epoch(state)) + 4)))
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 1
