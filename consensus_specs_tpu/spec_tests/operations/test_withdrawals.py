"""process_withdrawals operation tests (capella+; reference:
test/capella/block_processing/test_process_withdrawals.py shape)."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases_from
from ...test_infra.withdrawals import (
    get_expected_withdrawals, payload_with_expected_withdrawals,
    prepare_fully_withdrawable_validator,
    prepare_partially_withdrawable_validator, run_withdrawals_processing)


@with_all_phases_from("capella")
@spec_state_test
def test_no_withdrawals(spec, state):
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_one_full_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    assert payload.withdrawals[0].amount == state.balances[0]
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[0] == 0
    assert state.next_withdrawal_index == uint64(1)


@with_all_phases_from("capella")
@spec_state_test
def test_one_partial_withdrawal(spec, state):
    excess = 2000000000
    prepare_partially_withdrawable_validator(spec, state, 1,
                                             excess=excess)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    assert int(payload.withdrawals[0].amount) == excess
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[1] == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases_from("capella")
@spec_state_test
def test_mixed_full_and_partial(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    prepare_partially_withdrawable_validator(spec, state, 2)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 2
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_missing_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals = []
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_wrong_amount(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].amount = uint64(
        int(payload.withdrawals[0].amount) + 1)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_wrong_validator_index(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].validator_index = uint64(3)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_sweep_cursor_advances(spec, state):
    """The sweep cursor moves by the bound when the payload isn't
    full."""
    pre_cursor = int(state.next_withdrawal_validator_index)
    payload = payload_with_expected_withdrawals(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    bound = min(len(state.validators),
                int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP))
    assert int(state.next_withdrawal_validator_index) == \
        (pre_cursor + bound) % len(state.validators)


@with_all_phases_from("electra")
@spec_state_test
def test_pending_partial_withdrawal(spec, state):
    """Electra: a pending partial withdrawal request is honored by the
    sweep once withdrawable."""
    from ...test_infra.withdrawals import set_eth1_withdrawal_credentials
    index = 0
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    set_eth1_withdrawal_credentials(spec, state, index)
    state.balances[index] = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE) + int(amount))
    state.pending_partial_withdrawals = [spec.PendingPartialWithdrawal(
        validator_index=index, amount=amount,
        withdrawable_epoch=spec.get_current_epoch(state))]
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) >= 1
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0
