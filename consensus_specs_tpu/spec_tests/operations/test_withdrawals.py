"""process_withdrawals operation tests (capella+; reference:
test/capella/block_processing/test_process_withdrawals.py shape)."""
from ...ssz import uint64
from ...test_infra.context import spec_state_test, with_all_phases_from
from ...test_infra.withdrawals import (
    get_expected_withdrawals, payload_with_expected_withdrawals,
    prepare_fully_withdrawable_validator,
    prepare_partially_withdrawable_validator, run_withdrawals_processing)


@with_all_phases_from("capella")
@spec_state_test
def test_no_withdrawals(spec, state):
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_one_full_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    assert payload.withdrawals[0].amount == state.balances[0]
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[0] == 0
    assert state.next_withdrawal_index == uint64(1)


@with_all_phases_from("capella")
@spec_state_test
def test_one_partial_withdrawal(spec, state):
    excess = 2000000000
    prepare_partially_withdrawable_validator(spec, state, 1,
                                             excess=excess)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    assert int(payload.withdrawals[0].amount) == excess
    yield from run_withdrawals_processing(spec, state, payload)
    assert state.balances[1] == spec.MAX_EFFECTIVE_BALANCE


@with_all_phases_from("capella")
@spec_state_test
def test_mixed_full_and_partial(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    prepare_partially_withdrawable_validator(spec, state, 2)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 2
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_missing_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals = []
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_wrong_amount(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].amount = uint64(
        int(payload.withdrawals[0].amount) + 1)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_wrong_validator_index(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].validator_index = uint64(3)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_sweep_cursor_advances(spec, state):
    """The sweep cursor moves by the bound when the payload isn't
    full."""
    pre_cursor = int(state.next_withdrawal_validator_index)
    payload = payload_with_expected_withdrawals(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)
    bound = min(len(state.validators),
                int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP))
    assert int(state.next_withdrawal_validator_index) == \
        (pre_cursor + bound) % len(state.validators)


@with_all_phases_from("electra")
@spec_state_test
def test_pending_partial_withdrawal(spec, state):
    """Electra: a pending partial withdrawal request is honored by the
    sweep once withdrawable."""
    from ...test_infra.withdrawals import set_eth1_withdrawal_credentials
    index = 0
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    set_eth1_withdrawal_credentials(spec, state, index)
    state.balances[index] = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE) + int(amount))
    state.pending_partial_withdrawals = [spec.PendingPartialWithdrawal(
        validator_index=index, amount=amount,
        withdrawable_epoch=spec.get_current_epoch(state))]
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) >= 1
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0


from ...test_infra.withdrawals import (  # noqa: E402
    set_eth1_withdrawal_credentials)


@with_all_phases_from("capella")
@spec_state_test
def test_all_fully_withdrawable_in_sweep_window(spec, state):
    """Every validator in the sweep window fully withdrawable: payload
    carries the per-payload cap."""
    bound = min(int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP),
                len(state.validators),
                int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 4)
    for i in range(bound):
        prepare_fully_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == min(
        bound, int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_max_partial_withdrawals_in_one_payload(spec, state):
    cap = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    for i in range(cap + 2):
        prepare_partially_withdrawable_validator(
            spec, state, i % len(state.validators), excess=10**6)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == cap
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_withdrawable_epoch_but_0_balance(spec, state):
    """Fully withdrawable with zero balance: skipped by the sweep."""
    prepare_fully_withdrawable_validator(spec, state, 0, balance=0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != 0
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_withdrawable_epoch_but_0_effective_balance_not_0_balance(
        spec, state):
    """Zero EFFECTIVE balance with real balance: fully withdrawable
    (the sweep keys on withdrawable_epoch + balance)."""
    index = 0
    prepare_fully_withdrawable_validator(spec, state, index)
    state.validators[index].effective_balance = uint64(0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert any(int(w.validator_index) == index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_no_withdrawals_but_some_next_epoch(spec, state):
    """Withdrawability starting next epoch: nothing withdrawable yet."""
    index = 0
    prepare_fully_withdrawable_validator(spec, state, index)
    state.validators[index].withdrawable_epoch = uint64(
        int(spec.get_current_epoch(state)) + 1)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_partially_withdrawable_exact_max_balance(spec, state):
    """Balance exactly AT the max effective balance: NOT partially
    withdrawable (strict inequality)."""
    index = 0
    set_eth1_withdrawal_credentials(spec, state, index)
    state.validators[index].effective_balance = \
        spec.MAX_EFFECTIVE_BALANCE
    state.balances[index] = spec.MAX_EFFECTIVE_BALANCE
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_bls_credentials_not_withdrawable(spec, state):
    """0x00-credentialed validators never enter the sweep, however
    ripe."""
    index = 0
    v = state.validators[index]
    epoch = spec.get_current_epoch(state)
    v.exit_epoch = uint64(max(int(epoch) - 1, 0))
    v.withdrawable_epoch = epoch
    assert bytes(v.withdrawal_credentials)[:1] == \
        bytes(spec.BLS_WITHDRAWAL_PREFIX)
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_withdrawal_index_gap(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) > 0
    payload.withdrawals[0].index = uint64(
        int(payload.withdrawals[0].index) + 1)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_extra_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    extra = payload.withdrawals[0].copy()
    extra.index = uint64(int(extra.index) + 1)
    extra.validator_index = uint64(1)
    payload.withdrawals = list(payload.withdrawals) + [extra]
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_address_mismatch(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) > 0
    payload.withdrawals[0].address = b"\xde" * 20
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_empty_when_expected(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) > 0
    payload.withdrawals = []
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("electra")
@spec_state_test
def test_electra_pending_partial_before_sweep(spec, state):
    """EIP-7251 pending partial withdrawals drain before the sweep and
    consume the per-payload partial budget."""
    from ...test_infra.withdrawals import (
        set_compounding_withdrawal_credentials)
    index = 0
    set_compounding_withdrawal_credentials(spec, state, index)
    state.validators[index].effective_balance = \
        spec.MIN_ACTIVATION_BALANCE
    state.balances[index] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE) + 3 * 10**9)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=uint64(index), amount=uint64(10**9),
            withdrawable_epoch=spec.get_current_epoch(state)))
    payload = payload_with_expected_withdrawals(spec, state)
    assert any(int(w.validator_index) == index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("electra")
@spec_state_test
def test_electra_pending_partial_not_ripe(spec, state):
    """A pending partial whose withdrawable_epoch is in the future
    stays queued."""
    from ...test_infra.withdrawals import (
        set_compounding_withdrawal_credentials)
    index = 0
    set_compounding_withdrawal_credentials(spec, state, index)
    state.validators[index].effective_balance = \
        spec.MIN_ACTIVATION_BALANCE
    state.balances[index] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE) + 3 * 10**9)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=uint64(index), amount=uint64(10**9),
            withdrawable_epoch=uint64(
                int(spec.get_current_epoch(state)) + 4)))
    payload = payload_with_expected_withdrawals(spec, state)
    assert all(int(w.validator_index) != index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 1


# ---------------------------------------------------------------------------
# success-shape long tail (reference test_process_withdrawals.py)
# ---------------------------------------------------------------------------

@with_all_phases_from("capella")
@spec_state_test
def test_success_zero_expected_withdrawals(spec, state):
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)
    assert int(state.next_withdrawal_index) == 0


@with_all_phases_from("capella")
@spec_state_test
def test_success_mixed_fully_and_partial_withdrawable(spec, state):
    n = len(state.validators)
    fully = [0, 3]
    partial = [1, 4]
    for i in fully:
        prepare_fully_withdrawable_validator(spec, state, i)
    for i in partial:
        prepare_partially_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == len(fully) + len(partial)
    yield from run_withdrawals_processing(spec, state, payload)
    for i in fully:
        assert int(state.balances[i]) == 0
    for i in partial:
        assert int(state.balances[i]) == int(spec.MAX_EFFECTIVE_BALANCE)
    assert n == len(state.validators)  # sweep never mutates the registry


@with_all_phases_from("capella")
@spec_state_test
def test_success_all_fully_withdrawable(spec, state):
    """Every validator fully withdrawable: the payload carries exactly
    the per-payload bound, drained in registry order."""
    for i in range(len(state.validators)):
        prepare_fully_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    bound = min(len(state.validators),
                int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    assert len(payload.withdrawals) == bound
    yield from run_withdrawals_processing(spec, state, payload)
    for w in payload.withdrawals:
        assert int(state.balances[int(w.validator_index)]) == 0


@with_all_phases_from("capella")
@spec_state_test
def test_success_all_partially_withdrawable(spec, state):
    for i in range(len(state.validators)):
        prepare_partially_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == min(
        len(state.validators), int(spec.MAX_WITHDRAWALS_PER_PAYLOAD))
    yield from run_withdrawals_processing(spec, state, payload)
    for w in payload.withdrawals:
        assert int(state.balances[int(w.validator_index)]) \
            == int(spec.MAX_EFFECTIVE_BALANCE)


@with_all_phases_from("capella")
@spec_state_test
def test_success_max_per_slot_withdrawals(spec, state):
    """More fully-withdrawable validators than the per-payload bound:
    exactly MAX_WITHDRAWALS_PER_PAYLOAD are emitted."""
    bound = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    for i in range(min(bound + 2, len(state.validators))):
        prepare_fully_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == min(
        bound, len(state.validators))
    yield from run_withdrawals_processing(spec, state, payload)


# ---------------------------------------------------------------------------
# invalid-payload long tail
# ---------------------------------------------------------------------------

@with_all_phases_from("capella")
@spec_state_test
def test_invalid_non_withdrawable_non_empty_withdrawals(spec, state):
    """No one is withdrawable but the payload claims one is."""
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    payload.withdrawals = [spec.Withdrawal(
        index=0, validator_index=0, address=b"\xaa" * 20,
        amount=420)]
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_one_expected_full_withdrawal_and_duplicate_in_withdrawals(
        spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals = list(payload.withdrawals) \
        + [payload.withdrawals[0].copy()]
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_extra_withdrawal(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    extra = payload.withdrawals[0].copy()
    extra.index = uint64(int(extra.index) + 1)
    extra.validator_index = uint64(1)
    payload.withdrawals = list(payload.withdrawals) + [extra]
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_incorrect_withdrawal_index(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].index = uint64(
        int(payload.withdrawals[0].index) + 1)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_incorrect_address_full(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].address = b"\xff" * 20
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_incorrect_address_partial(spec, state):
    prepare_partially_withdrawable_validator(spec, state, 1)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].address = b"\xff" * 20
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_incorrect_amount_partial(spec, state):
    prepare_partially_withdrawable_validator(spec, state, 1)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals[0].amount = uint64(
        int(payload.withdrawals[0].amount) + 1)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_one_of_many_incorrectly_full(spec, state):
    for i in range(3):
        prepare_fully_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 3
    # corrupt the middle one
    payload.withdrawals[1].amount = uint64(0)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_one_of_many_incorrectly_partial(spec, state):
    for i in range(3):
        prepare_partially_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 3
    payload.withdrawals[1].validator_index = uint64(
        int(payload.withdrawals[1].validator_index) + 10)
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


@with_all_phases_from("capella")
@spec_state_test
def test_invalid_max_per_slot_full_withdrawals_and_one_less_in_withdrawals(
        spec, state):
    bound = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    for i in range(min(bound + 2, len(state.validators))):
        prepare_fully_withdrawable_validator(spec, state, i)
    payload = payload_with_expected_withdrawals(spec, state)
    payload.withdrawals = list(payload.withdrawals)[:-1]
    yield from run_withdrawals_processing(spec, state, payload,
                                          valid=False)


# ---------------------------------------------------------------------------
# withdrawability edge states
# ---------------------------------------------------------------------------

@with_all_phases_from("capella")
@spec_state_test
def test_withdrawable_epoch_but_0_balance(spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0, balance=0)
    state.validators[0].effective_balance = 0
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_withdrawable_epoch_but_0_effective_balance_nonzero_balance(
        spec, state):
    prepare_fully_withdrawable_validator(spec, state, 0,
                                         balance=100000000)
    state.validators[0].effective_balance = 0
    payload = payload_with_expected_withdrawals(spec, state)
    # a full withdrawal drains the actual balance regardless of EB
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)
    assert int(state.balances[0]) == 0


@with_all_phases_from("capella")
@spec_state_test
def test_no_withdrawals_but_some_next_epoch(spec, state):
    """Validators become withdrawable next epoch: nothing this slot."""
    epoch = spec.get_current_epoch(state)
    for i in range(3):
        set_eth1_withdrawal_credentials(spec, state, i)
        state.validators[i].exit_epoch = epoch
        state.validators[i].withdrawable_epoch = uint64(int(epoch) + 1)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_success_no_excess_balance(spec, state):
    """Exactly max effective balance: not partially withdrawable."""
    set_eth1_withdrawal_credentials(spec, state, 1)
    state.validators[1].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    state.balances[1] = spec.MAX_EFFECTIVE_BALANCE
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_success_excess_balance_but_no_max_effective_balance(spec, state):
    """Excess balance over a sub-max effective balance: not partially
    withdrawable."""
    set_eth1_withdrawal_credentials(spec, state, 1)
    state.validators[1].effective_balance = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE)
        - int(spec.EFFECTIVE_BALANCE_INCREMENT))
    state.balances[1] = uint64(int(spec.MAX_EFFECTIVE_BALANCE) + 1)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_success_one_partial_withdrawable_not_yet_active(spec, state):
    """Activation status doesn't gate partial withdrawability."""
    prepare_partially_withdrawable_validator(spec, state, 1)
    state.validators[1].activation_epoch = uint64(
        int(spec.get_current_epoch(state)) + 4)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_success_one_partial_withdrawable_in_exit_queue(spec, state):
    prepare_partially_withdrawable_validator(spec, state, 1)
    state.validators[1].exit_epoch = uint64(
        int(spec.get_current_epoch(state)) + 1)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_success_one_partial_withdrawable_exited(spec, state):
    prepare_partially_withdrawable_validator(spec, state, 1)
    state.validators[1].exit_epoch = spec.get_current_epoch(state)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_success_one_partial_withdrawable_active_and_slashed(spec, state):
    prepare_partially_withdrawable_validator(spec, state, 1)
    state.validators[1].slashed = True
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_success_two_partial_withdrawable(spec, state):
    prepare_partially_withdrawable_validator(spec, state, 0)
    prepare_partially_withdrawable_validator(spec, state, 1)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 2
    yield from run_withdrawals_processing(spec, state, payload)


# ---------------------------------------------------------------------------
# randomized sweeps (reference test_random_full/partial_withdrawals_N)
# ---------------------------------------------------------------------------

def _run_random_withdrawals(spec, state, rng):
    for i in range(len(state.validators)):
        roll = rng.random()
        if roll < 0.25:
            prepare_fully_withdrawable_validator(spec, state, i)
        elif roll < 0.5:
            prepare_partially_withdrawable_validator(
                spec, state, i, excess=rng.randrange(1, 10**9))
    payload = payload_with_expected_withdrawals(spec, state)
    yield from run_withdrawals_processing(spec, state, payload)


@with_all_phases_from("capella")
@spec_state_test
def test_random_withdrawals_0(spec, state):
    import random
    yield from _run_random_withdrawals(spec, state, random.Random(444))


@with_all_phases_from("capella")
@spec_state_test
def test_random_withdrawals_1(spec, state):
    import random
    yield from _run_random_withdrawals(spec, state, random.Random(420))


@with_all_phases_from("capella")
@spec_state_test
def test_random_withdrawals_2(spec, state):
    import random
    yield from _run_random_withdrawals(spec, state, random.Random(200))


@with_all_phases_from("capella")
@spec_state_test
def test_random_withdrawals_3(spec, state):
    import random
    yield from _run_random_withdrawals(spec, state, random.Random(2000000))


# ---------------------------------------------------------------------------
# electra pending partial withdrawals (reference electra
# test_process_withdrawals.py pending_withdrawals battery)
# ---------------------------------------------------------------------------

from ...test_infra.withdrawals import prepare_pending_withdrawal  # noqa: E402


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_one_skipped_one_effective(spec, state):
    index_0, index_1 = 3, 5
    pending_0 = prepare_pending_withdrawal(spec, state, index_0)
    pending_1 = prepare_pending_withdrawal(spec, state, index_1)
    # validator 0 loses its excess: its request is skipped
    state.balances[index_0] = spec.MIN_ACTIVATION_BALANCE
    assert list(state.pending_partial_withdrawals) \
        == [pending_0, pending_1]
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    assert int(payload.withdrawals[0].validator_index) == index_1
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_next_epoch(spec, state):
    index = len(state.validators) // 2
    pending = prepare_pending_withdrawal(
        spec, state, index,
        withdrawable_epoch=uint64(int(spec.get_current_epoch(state)) + 1))
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)
    # not ripe yet: stays queued
    assert list(state.pending_partial_withdrawals) == [pending]


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_at_max(spec, state):
    bound = int(spec.MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP)
    requests = [prepare_pending_withdrawal(spec, state, i)
                for i in range(bound + 1)]
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == bound
    yield from run_withdrawals_processing(spec, state, payload)
    # the overflow request survives the sweep
    assert list(state.pending_partial_withdrawals) == requests[bound:]


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_exiting_validator(spec, state):
    index = len(state.validators) // 2
    pending = prepare_pending_withdrawal(spec, state, index)
    spec.initiate_validator_exit(state, pending.validator_index)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)
    # consumed without effect
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_low_effective_balance(spec, state):
    index = len(state.validators) // 2
    pending = prepare_pending_withdrawal(spec, state, index)
    state.validators[int(pending.validator_index)].effective_balance = \
        uint64(int(spec.MIN_ACTIVATION_BALANCE)
               - int(spec.EFFECTIVE_BALANCE_INCREMENT))
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_no_excess_balance(spec, state):
    index = len(state.validators) // 2
    pending = prepare_pending_withdrawal(spec, state, index)
    state.balances[int(pending.validator_index)] = \
        spec.MIN_ACTIVATION_BALANCE
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_with_ineffective_sweep_on_top(spec, state):
    """The pending withdrawal drains the excess, so the sweep on top of
    it finds nothing partially withdrawable."""
    index = min(len(state.validators),
                int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)) // 2
    prepare_pending_withdrawal(
        spec, state, index,
        effective_balance=spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    assert spec.is_partially_withdrawable_validator(
        state.validators[index], state.balances[index])
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 1
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0
    # the sweep found no second withdrawal for the same validator
    assert not spec.is_partially_withdrawable_validator(
        state.validators[index], state.balances[index])


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_with_effective_sweep_on_top(spec, state):
    """Excess beyond the pending amount: the sweep emits a SECOND
    withdrawal for the same validator."""
    index = min(len(state.validators),
                int(spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)) // 2
    prepare_pending_withdrawal(
        spec, state, index,
        effective_balance=spec.MAX_EFFECTIVE_BALANCE_ELECTRA,
        amount=2_000_000_000)
    # extra excess beyond the pending amount keeps the validator
    # partially withdrawable AFTER the pending request drains
    state.balances[index] = uint64(
        int(state.balances[index]) + 1_000_000_000)
    payload = payload_with_expected_withdrawals(spec, state)
    assert len(payload.withdrawals) == 2
    assert all(int(w.validator_index) == index
               for w in payload.withdrawals)
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_with_sweep_different_validator(spec, state):
    """Pending withdrawal for one validator, sweepable excess on
    another: both are in the payload."""
    index_0, index_1 = 1, 3
    prepare_pending_withdrawal(spec, state, index_0)
    prepare_partially_withdrawable_validator(spec, state, index_1)
    payload = payload_with_expected_withdrawals(spec, state)
    assert sorted(int(w.validator_index)
                  for w in payload.withdrawals) == [index_0, index_1]
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_mixed_with_sweep_and_fully_withdrawable(
        spec, state):
    prepare_pending_withdrawal(spec, state, 1)
    prepare_fully_withdrawable_validator(spec, state, 3)
    prepare_partially_withdrawable_validator(spec, state, 5)
    payload = payload_with_expected_withdrawals(spec, state)
    assert sorted(int(w.validator_index)
                  for w in payload.withdrawals) == [1, 3, 5]
    yield from run_withdrawals_processing(spec, state, payload)
    assert len(state.pending_partial_withdrawals) == 0
