"""EIP-7002 EL-triggered withdrawal request operation tests (electra+).

Reference battery:
test/electra/block_processing/test_process_withdrawal_request.py (29
cases).  Request processing is no-fault — malformed/ineligible requests
are ignored, so "ignored" cases assert the state root is untouched.
"""
from ...ssz import uint64
from ...test_infra.context import (
    spec_state_test, with_all_phases_from, with_presets)
from ...test_infra.keys import pubkeys
from ...test_infra.withdrawals import (
    set_eth1_withdrawal_credentials,
    set_compounding_withdrawal_credentials)
from ...test_infra.electra_requests import (
    DEFAULT_ADDRESS, WRONG_ADDRESS, age_past_exit_gate,
    run_request_processing, make_inactive,
    add_pending_partial_withdrawal)


def _full_exit_request(spec, state, index, address=DEFAULT_ADDRESS):
    return spec.WithdrawalRequest(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT)


def _partial_request(spec, state, index, amount, address=DEFAULT_ADDRESS):
    return spec.WithdrawalRequest(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey,
        amount=uint64(amount))


def _stage_partial(spec, state, index, excess):
    """Compounding validator at MIN_ACTIVATION_BALANCE effective with
    `excess` Gwei on top — the partial-withdrawal sweet spot."""
    set_compounding_withdrawal_credentials(spec, state, index,
                                           address=DEFAULT_ADDRESS)
    state.validators[index].effective_balance = \
        spec.MIN_ACTIVATION_BALANCE
    state.balances[index] = uint64(
        int(spec.MIN_ACTIVATION_BALANCE) + excess)


# ---------------------------------------------------------------------------
# full exits
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_basic_withdrawal_request(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 1,
                                    address=DEFAULT_ADDRESS)
    request = _full_exit_request(spec, state, 1)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert state.validators[1].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_basic_withdrawal_request_with_first_validator(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert state.validators[0].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_basic_withdrawal_request_with_compounding_credentials(spec, state):
    age_past_exit_gate(spec, state)
    set_compounding_withdrawal_credentials(spec, state, 0,
                                           address=DEFAULT_ADDRESS)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert state.validators[0].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@with_presets(["minimal"], "filling the queue is preset-sized")
@spec_state_test
def test_basic_withdrawal_request_with_full_partial_withdrawal_queue(spec, state):
    # the queue-limit early-out only applies to partial requests; a full
    # exit goes through even with the queue at its limit
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    limit = int(spec.PENDING_PARTIAL_WITHDRAWALS_LIMIT)
    for _ in range(limit):
        add_pending_partial_withdrawal(spec, state, 1)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert state.validators[0].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_source_address(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    request = _full_exit_request(spec, state, 0, address=WRONG_ADDRESS)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_withdrawal_credential_prefix(spec, state):
    # 0x00 BLS credentials are not execution credentials
    age_past_exit_gate(spec, state)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_on_withdrawal_request_initiated_validator(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    spec.initiate_validator_exit(state, 0)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_activation_epoch_less_than_shard_committee_period(spec, state):
    # no aging: current epoch < activation + SHARD_COMMITTEE_PERIOD
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_unknown_pubkey(spec, state):
    age_past_exit_gate(spec, state)
    request = spec.WithdrawalRequest(
        source_address=DEFAULT_ADDRESS,
        validator_pubkey=pubkeys[len(state.validators) + 7],
        amount=spec.FULL_EXIT_REQUEST_AMOUNT)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_incorrect_inactive_validator(spec, state):
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    make_inactive(spec, state, 0)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_full_exit_request_has_partial_withdrawal(spec, state):
    # a full exit is deferred while pending partials exist for the
    # validator (pending_balance_to_withdraw != 0)
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    add_pending_partial_withdrawal(spec, state, 0)
    request = _full_exit_request(spec, state, 0)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


# ---------------------------------------------------------------------------
# partial withdrawals
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_basic_partial_withdrawal_request(spec, state):
    age_past_exit_gate(spec, state)
    excess = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, excess)
    request = _partial_request(spec, state, 0, excess)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert len(state.pending_partial_withdrawals) == 1
    assert int(state.pending_partial_withdrawals[0].amount) == excess
    # partial withdrawals never initiate an exit
    assert state.validators[0].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_all_phases_from("electra")
@spec_state_test
def test_basic_partial_withdrawal_request_higher_excess_balance(spec, state):
    # excess above the requested amount: full amount is withdrawn
    age_past_exit_gate(spec, state)
    amount = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, 2 * amount)
    request = _partial_request(spec, state, 0, amount)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert int(state.pending_partial_withdrawals[0].amount) == amount


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_request_with_high_amount(spec, state):
    # request above the excess: only the excess is withdrawable
    age_past_exit_gate(spec, state)
    excess = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, excess)
    request = _partial_request(spec, state, 0, 3 * excess)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert int(state.pending_partial_withdrawals[0].amount) == excess


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_request_with_pending_withdrawals(spec, state):
    # pending amounts reduce the remaining excess
    age_past_exit_gate(spec, state)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, 3 * unit)
    add_pending_partial_withdrawal(spec, state, 0, amount=unit)
    request = _partial_request(spec, state, 0, 4 * unit)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert len(state.pending_partial_withdrawals) == 2
    assert int(state.pending_partial_withdrawals[1].amount) == 2 * unit


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_request_with_low_amount(spec, state):
    age_past_exit_gate(spec, state)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, unit)
    request = _partial_request(spec, state, 0, unit // 4)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert int(state.pending_partial_withdrawals[0].amount) == unit // 4


@with_all_phases_from("electra")
@with_presets(["minimal"], "filling the queue is preset-sized")
@spec_state_test
def test_partial_withdrawal_queue_full(spec, state):
    age_past_exit_gate(spec, state)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, unit)
    limit = int(spec.PENDING_PARTIAL_WITHDRAWALS_LIMIT)
    for _ in range(limit):
        add_pending_partial_withdrawal(spec, state, 1)
    request = _partial_request(spec, state, 0, unit)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_no_compounding_credentials(spec, state):
    # 0x01 credentials cannot take partial withdrawals
    age_past_exit_gate(spec, state)
    set_eth1_withdrawal_credentials(spec, state, 0,
                                    address=DEFAULT_ADDRESS)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.balances[0] = uint64(int(spec.MIN_ACTIVATION_BALANCE) + unit)
    request = _partial_request(spec, state, 0, unit)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_no_excess_balance(spec, state):
    age_past_exit_gate(spec, state)
    _stage_partial(spec, state, 0, 0)
    request = _partial_request(
        spec, state, 0, int(spec.EFFECTIVE_BALANCE_INCREMENT))
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_insufficient_effective_balance(spec, state):
    age_past_exit_gate(spec, state)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, unit)
    state.validators[0].effective_balance = uint64(
        int(spec.MIN_ACTIVATION_BALANCE) - unit)
    request = _partial_request(spec, state, 0, unit)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_pending_withdrawals_consume_all_excess_balance(spec, state):
    # pending amounts already cover the excess: nothing left to withdraw
    age_past_exit_gate(spec, state)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, unit)
    add_pending_partial_withdrawal(spec, state, 0, amount=unit)
    pre_len = len(state.pending_partial_withdrawals)
    request = _partial_request(spec, state, 0, unit)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)
    assert len(state.pending_partial_withdrawals) == pre_len


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_incorrect_source_address(spec, state):
    age_past_exit_gate(spec, state)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, unit)
    request = _partial_request(spec, state, 0, unit,
                               address=WRONG_ADDRESS)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_on_exit_initiated_validator(
        spec, state):
    age_past_exit_gate(spec, state)
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, unit)
    spec.initiate_validator_exit(state, 0)
    request = _partial_request(spec, state, 0, unit)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_activation_epoch_less_than_shard_committee_period(spec, state):
    unit = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 0, unit)
    request = _partial_request(spec, state, 0, unit)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)


# ---------------------------------------------------------------------------
# remaining reference names (round 5 completion)
# ---------------------------------------------------------------------------

@with_all_phases_from("electra")
@spec_state_test
def test_basic_partial_withdrawal_request_lower_than_excess_balance(
        spec, state):
    """Excess balance LOWER than the requested amount (reference
    :422): the request queues with the amount CAPPED at the excess
    (process_withdrawal_request's min() at queue time)."""
    age_past_exit_gate(spec, state)
    excess = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    amount = 2 * excess
    _stage_partial(spec, state, 1, excess)
    request = _partial_request(spec, state, 1, amount)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert len(state.pending_partial_withdrawals) == 1
    assert int(state.pending_partial_withdrawals[0].amount) == excess


@with_all_phases_from("electra")
@spec_state_test
def test_insufficient_balance(spec, state):
    """Full exit with balance below the activation floor: ignored...
    more precisely the EXIT path only needs an active validator, so the
    meaningful insufficient-balance gate is the PARTIAL path — a
    request against zero excess queues nothing."""
    age_past_exit_gate(spec, state)
    _stage_partial(spec, state, 1, 0)
    state.balances[1] = uint64(int(spec.MIN_ACTIVATION_BALANCE) // 2)
    request = _partial_request(
        spec, state, 1, int(spec.EFFECTIVE_BALANCE_INCREMENT))
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_incorrect_withdrawal_credential_prefix(
        spec, state):
    """Compounding credentials with the prefix corrupted to 0x00 BLS
    (reference namesake): fails has_execution_withdrawal_credential,
    request ignored."""
    age_past_exit_gate(spec, state)
    _stage_partial(spec, state, 1,
                   int(spec.EFFECTIVE_BALANCE_INCREMENT))
    creds = bytes(state.validators[1].withdrawal_credentials)
    state.validators[1].withdrawal_credentials =         bytes(spec.BLS_WITHDRAWAL_PREFIX) + creds[1:]
    request = _partial_request(
        spec, state, 1, int(spec.EFFECTIVE_BALANCE_INCREMENT))
    yield from run_request_processing(
        spec, state, "withdrawal_request", request, mutates=False)
    assert len(state.pending_partial_withdrawals) == 0


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_request_with_high_balance(spec, state):
    """Max-EB compounding validator with a big excess: the requested
    amount queues in full."""
    age_past_exit_gate(spec, state)
    set_compounding_withdrawal_credentials(spec, state, 1,
                                           address=DEFAULT_ADDRESS)
    state.validators[1].effective_balance = \
        spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    state.balances[1] = uint64(
        int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
        + 8 * int(spec.EFFECTIVE_BALANCE_INCREMENT))
    amount = 4 * int(spec.EFFECTIVE_BALANCE_INCREMENT)
    request = _partial_request(spec, state, 1, amount)
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert len(state.pending_partial_withdrawals) == 1
    assert int(state.pending_partial_withdrawals[0].amount) == amount


@with_all_phases_from("electra")
@spec_state_test
def test_partial_withdrawal_request_with_pending_withdrawals_and_high_amount(
        spec, state):
    """Reference :503 SUCCESS case: a near-full pending queue, but the
    validator's balance still carries excess — a UINT64_MAX request
    queues anyway."""
    age_past_exit_gate(spec, state)
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _stage_partial(spec, state, 1, incr)
    pre_queue = int(spec.PENDING_PARTIAL_WITHDRAWALS_LIMIT) - 1
    for _ in range(pre_queue):
        add_pending_partial_withdrawal(spec, state, 1, incr)
    # balance high enough to leave excess past all the pendings
    state.balances[1] = spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    request = _partial_request(spec, state, 1, uint64(2**64 - 1))
    yield from run_request_processing(
        spec, state, "withdrawal_request", request)
    assert len(state.pending_partial_withdrawals) == pre_queue + 1
